"""Process-level e2e: real manager + agent OS processes, CLI-applied CR.

The reference's e2e tier builds the manager image, deploys it to a Kind
cluster, applies a sample CR, and scrapes the secured /metrics endpoint
with a token (test/e2e/e2e_test.go:48-337). This is the same story without
a container runtime: spawn ``python -m kubeinfer_tpu.manager`` and two
``python -m kubeinfer_tpu.agent`` processes, apply a sample YAML via
``python -m kubeinfer_tpu.ctl``, and assert the service reaches Running,
the metrics endpoint enforces its token, and SIGTERM shuts everything
down cleanly.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from kubeinfer_tpu.controlplane.httpstore import RemoteStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SAMPLE = os.path.join(REPO, "deploy", "samples", "llmservice_cache.yaml")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def wait_until(pred, timeout_s: float, what: str):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.2)
    pytest.fail(f"timed out waiting for {what}")


def http_get(url: str, token: str = "") -> tuple[int, str]:
    req = urllib.request.Request(url)
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(req, timeout=5) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, ""
    except OSError:
        return 0, ""  # not up yet


def start_manager(
    procs, env, token_file, store_port, metrics_port, health_port, *extra
):
    """Spawn the manager process and wait for both probes. One home for
    the CLI flag set so the e2e tests cannot drift apart."""
    procs.append(subprocess.Popen(
        [
            sys.executable, "-m", "kubeinfer_tpu.manager",
            "--store-bind-address", f"127.0.0.1:{store_port}",
            "--metrics-bind-address", f"127.0.0.1:{metrics_port}",
            "--health-probe-bind-address", f"127.0.0.1:{health_port}",
            "--auth-token-file", str(token_file),
            "--tick-interval", "0.2",
            *extra,
        ],
        env=env, cwd=REPO,
    ))
    wait_until(
        lambda: http_get(f"http://127.0.0.1:{health_port}/healthz")[0] == 200,
        60, "manager /healthz",
    )
    wait_until(
        lambda: http_get(f"http://127.0.0.1:{health_port}/readyz")[0] == 200,
        60, "manager /readyz",
    )


def ctl_apply(sample, store_addr, token_file, env):
    apply = subprocess.run(
        [
            sys.executable, "-m", "kubeinfer_tpu.ctl",
            "--store", store_addr, "--token-file", str(token_file),
            "apply", "-f", sample,
        ],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    assert apply.returncode == 0, apply.stderr
    return apply


def phase_running(store, name):
    def running() -> bool:
        try:
            svc = store.get("LLMService", name)
        except (KeyError, OSError):
            return False
        return svc["status"]["phase"] == "Running"

    return running


@pytest.fixture()
def subprocess_env(tmp_path):
    from tests.conftest import scrubbed_pythonpath

    env = dict(os.environ)
    # subprocesses must not touch the experimental axon TPU tunnel — and
    # must not inherit this box's axon sitecustomize via PYTHONPATH
    # (its startup jax import can hang on relay load)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = scrubbed_pythonpath()
    return env


def test_manager_agents_cli_end_to_end(tmp_path, subprocess_env):
    token_file = tmp_path / "token"
    token_file.write_text("e2e-secret\n")

    store_port, metrics_port, health_port = free_port(), free_port(), free_port()
    store_addr = f"http://127.0.0.1:{store_port}"
    procs: list[subprocess.Popen] = []
    try:
        start_manager(
            procs, subprocess_env, token_file,
            store_port, metrics_port, health_port,
            "--node-ttl", "10",
        )

        for i in range(2):
            agent_env = dict(subprocess_env)
            agent_env.update(
                NODE_NAME=f"node-{i}",
                STORE_ADDR=store_addr,
                STORE_TOKEN_FILE=str(token_file),
                MODEL_PATH=str(tmp_path / f"models-{i}"),
                GPU_CAPACITY="8",
                GPU_MEMORY="16Gi",
                HEARTBEAT_INTERVAL_S="0.3",
                KUBEINFER_DOWNLOADER="mock",
                LEASE_DURATION_S="2",
                LEASE_RENEW_S="1",
                LEASE_RETRY_S="0.3",
            )
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "kubeinfer_tpu.agent"],
                env=agent_env, cwd=REPO,
            ))

        store = RemoteStore(store_addr, token="e2e-secret")
        wait_until(lambda: len(store.list("Node")) == 2, 60, "2 node heartbeats")

        # apply the sample CR through the CLI binary
        apply = ctl_apply(SAMPLE, store_addr, token_file, subprocess_env)
        assert "created" in apply.stdout

        wait_until(
            phase_running(store, "llm-cache-demo"), 90,
            "LLMService phase Running",
        )

        svc = store.get("LLMService", "llm-cache-demo")
        assert svc["status"]["availableReplicas"] == 3
        assert all(svc["status"]["placements"])
        assert svc["status"]["cacheCoordinator"]  # a coordinator was elected

        # CLI table output
        get = subprocess.run(
            [
                sys.executable, "-m", "kubeinfer_tpu.ctl",
                "--store", store_addr, "--token-file", str(token_file),
                "get", "llmservices",
            ],
            env=subprocess_env, cwd=REPO, capture_output=True, text=True,
            timeout=60,
        )
        assert get.returncode == 0
        assert "llm-cache-demo" in get.stdout and "Running" in get.stdout

        # secured metrics endpoint (ref e2e_test.go:176-267 parity)
        code, _ = http_get(f"http://127.0.0.1:{metrics_port}/metrics")
        assert code == 401
        code, body = http_get(
            f"http://127.0.0.1:{metrics_port}/metrics", token="e2e-secret"
        )
        assert code == 200
        assert "kubeinfer_llmservice_total 1" in body
        assert "kubeinfer_reconcile_total" in body
        assert "kubeinfer_solve_duration_seconds" in body

        # clean shutdown on SIGTERM (ref signal handling parity)
        for p in reversed(procs):
            p.send_signal(signal.SIGTERM)
        for p in procs:
            assert p.wait(timeout=30) == 0
        procs.clear()
    finally:
        for p in procs:
            p.kill()
            p.wait(timeout=10)


NATIVE_SAMPLE = os.path.join(REPO, "deploy", "samples", "llmservice_native.yaml")


def test_native_runtime_end_to_end(tmp_path, subprocess_env):
    """runtime: native through the full stack: the agent spawns the
    in-framework JAX engine (`python -m kubeinfer_tpu.inference.server`)
    as a real subprocess, the replica goes Ready only after the engine's
    /health, and the served OpenAI-compatible endpoint answers a
    completion. This is the e2e proof that the scheduler, agent
    lifecycle, and native inference tier compose.
    """
    import json

    token_file = tmp_path / "token"
    token_file.write_text("e2e-secret\n")

    store_port, metrics_port, health_port = free_port(), free_port(), free_port()
    serve_port = free_port()
    store_addr = f"http://127.0.0.1:{store_port}"
    procs: list[subprocess.Popen] = []
    try:
        start_manager(
            procs, subprocess_env, token_file,
            store_port, metrics_port, health_port,
        )

        agent_env = dict(subprocess_env)
        agent_env.update(
            NODE_NAME="node-0",
            STORE_ADDR=store_addr,
            STORE_TOKEN_FILE=str(token_file),
            MODEL_PATH=str(tmp_path / "models"),
            GPU_CAPACITY="8",
            GPU_MEMORY="16Gi",
            HEARTBEAT_INTERVAL_S="0.3",
            KUBEINFER_DOWNLOADER="mock",
            START_RUNTIMES="1",
            # engine flags ride the VLLM_* env contract: the random-init
            # tiny preset needs no checkpoint on disk, and the port must
            # not collide with other suites on this box
            VLLM_PORT=str(serve_port),
            VLLM_EXTRA_ARGS="--random-init",
            # 1-CPU-core box: first jax compile in the spawned server is
            # slow; the replica must not go Ready before /health does
            VLLM_HEALTH_TIMEOUT_S="150",
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "kubeinfer_tpu.agent"],
            env=agent_env, cwd=REPO,
        ))

        store = RemoteStore(store_addr, token="e2e-secret")
        wait_until(lambda: len(store.list("Node")) == 1, 60, "node heartbeat")

        ctl_apply(NATIVE_SAMPLE, store_addr, token_file, subprocess_env)

        # generous: the engine subprocess imports jax (slow on one CPU
        # core) before /health turns 200 and the replica goes Ready
        wait_until(
            phase_running(store, "llm-native-demo"), 180,
            "native LLMService Running",
        )

        # the engine the agent spawned must actually serve inference.
        # /health does NOT imply the generate path is compiled — prefill
        # and the decode scan jit lazily on this first request, so it
        # carries the compile; budget accordingly (the server's own
        # internal request timeout is 300s).
        req = urllib.request.Request(
            f"http://127.0.0.1:{serve_port}/v1/completions",
            data=json.dumps(
                {"prompt": [1, 2, 3, 4], "max_tokens": 4}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as resp:
            body = json.loads(resp.read().decode())
        assert body["choices"], body
        assert body["usage"]["completion_tokens"] >= 1

        # teardown kills the whole tree, engine subprocess included
        for p in reversed(procs):
            p.send_signal(signal.SIGTERM)
        for p in procs:
            assert p.wait(timeout=40) == 0
        procs.clear()
        # the serving port must be closed once the agent is gone
        wait_until(
            lambda: http_get(f"http://127.0.0.1:{serve_port}/health")[0] == 0,
            20, "engine port released",
        )
    finally:
        for p in procs:
            p.kill()
            p.wait(timeout=10)


def test_manager_agents_tls_end_to_end(tmp_path, subprocess_env):
    """The full process stack once over https (r2 verdict item 4): TLS
    manager store + metrics, agents and CLI verifying via STORE_CA_FILE,
    metrics 401/200 posture over TLS."""
    import ssl

    token_file = tmp_path / "token"
    token_file.write_text("e2e-tls-secret\n")
    cert, key = tmp_path / "cert.pem", tmp_path / "key.pem"
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
            "-keyout", str(key), "-out", str(cert), "-days", "1",
            "-subj", "/CN=127.0.0.1",
            "-addext", "subjectAltName=IP:127.0.0.1",
        ],
        check=True, capture_output=True,
    )

    def https_get(url, token=""):
        ctx = ssl.create_default_context(cafile=str(cert))
        req = urllib.request.Request(url)
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        try:
            with urllib.request.urlopen(req, timeout=5, context=ctx) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, ""
        except OSError:
            return 0, ""

    store_port, metrics_port, health_port = (
        free_port(), free_port(), free_port(),
    )
    store_addr = f"https://127.0.0.1:{store_port}"
    procs: list[subprocess.Popen] = []
    try:
        procs.append(subprocess.Popen(
            [
                sys.executable, "-m", "kubeinfer_tpu.manager",
                "--store-bind-address", f"127.0.0.1:{store_port}",
                "--metrics-bind-address", f"127.0.0.1:{metrics_port}",
                "--health-probe-bind-address", f"127.0.0.1:{health_port}",
                "--auth-token-file", str(token_file),
                "--tick-interval", "0.2",
                "--tls-cert-file", str(cert),
                "--tls-key-file", str(key),
            ],
            env=subprocess_env, cwd=REPO,
        ))
        wait_until(
            lambda: https_get(
                f"https://127.0.0.1:{health_port}/readyz"
            )[0] == 200,
            60, "manager /readyz over TLS",
        )

        agent_env = dict(subprocess_env)
        agent_env.update(
            NODE_NAME="node-tls",
            STORE_ADDR=store_addr,
            STORE_TOKEN_FILE=str(token_file),
            STORE_CA_FILE=str(cert),
            MODEL_PATH=str(tmp_path / "models"),
            GPU_CAPACITY="8",
            GPU_MEMORY="16Gi",
            HEARTBEAT_INTERVAL_S="0.3",
            KUBEINFER_DOWNLOADER="mock",
            LEASE_DURATION_S="2",
            LEASE_RENEW_S="1",
            LEASE_RETRY_S="0.3",
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "kubeinfer_tpu.agent"],
            env=agent_env, cwd=REPO,
        ))

        store = RemoteStore(
            store_addr, token="e2e-tls-secret", ca_file=str(cert)
        )
        wait_until(
            lambda: len(store.list("Node")) == 1, 60,
            "node heartbeat over TLS",
        )

        # CLI through the https store with --ca-file
        apply = subprocess.run(
            [
                sys.executable, "-m", "kubeinfer_tpu.ctl",
                "--store", store_addr, "--token-file", str(token_file),
                "--ca-file", str(cert),
                "apply", "-f", SAMPLE,
            ],
            env=subprocess_env, cwd=REPO, capture_output=True, text=True,
            timeout=60,
        )
        assert apply.returncode == 0, apply.stderr

        wait_until(
            phase_running(store, "llm-cache-demo"), 90,
            "LLMService phase Running over TLS",
        )

        # secured metrics posture, over TLS (ref e2e_test.go:176-267)
        code, _ = https_get(f"https://127.0.0.1:{metrics_port}/metrics")
        assert code == 401
        code, body = https_get(
            f"https://127.0.0.1:{metrics_port}/metrics",
            token="e2e-tls-secret",
        )
        assert code == 200
        assert "kubeinfer_llmservice_total 1" in body

        for p in reversed(procs):
            p.send_signal(signal.SIGTERM)
        for p in procs:
            assert p.wait(timeout=30) == 0
        procs.clear()
    finally:
        for p in procs:
            p.kill()
            p.wait(timeout=10)


def test_manager_kill9_restart_durable_state(tmp_path, subprocess_env):
    """Durable control plane (r3 verdict item 3): SIGKILL the manager
    mid-fleet, restart it on the same --data-dir, and the fleet must
    reconverge to Running WITHOUT re-applying any CR — services,
    workloads, nodes and leases all come back from the journal, and the
    resourceVersion counter continues (no CAS reset)."""
    token_file = tmp_path / "token"
    token_file.write_text("e2e-secret\n")
    data_dir = tmp_path / "state"

    store_port, metrics_port, health_port = (
        free_port(), free_port(), free_port(),
    )
    store_addr = f"http://127.0.0.1:{store_port}"
    procs: list[subprocess.Popen] = []
    try:
        start_manager(
            procs, subprocess_env, token_file,
            store_port, metrics_port, health_port,
            "--node-ttl", "10", "--data-dir", str(data_dir),
        )
        for i in range(2):
            agent_env = dict(subprocess_env)
            agent_env.update(
                NODE_NAME=f"node-{i}",
                STORE_ADDR=store_addr,
                STORE_TOKEN_FILE=str(token_file),
                MODEL_PATH=str(tmp_path / f"models-{i}"),
                GPU_CAPACITY="8",
                GPU_MEMORY="16Gi",
                HEARTBEAT_INTERVAL_S="0.3",
                KUBEINFER_DOWNLOADER="mock",
                LEASE_DURATION_S="2",
                LEASE_RENEW_S="1",
                LEASE_RETRY_S="0.3",
            )
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "kubeinfer_tpu.agent"],
                env=agent_env, cwd=REPO,
            ))

        store = RemoteStore(store_addr, token="e2e-secret")
        wait_until(lambda: len(store.list("Node")) == 2, 60, "2 node heartbeats")
        ctl_apply(SAMPLE, store_addr, token_file, subprocess_env)
        wait_until(
            phase_running(store, "llm-cache-demo"), 90,
            "LLMService phase Running",
        )
        rv_before = store.get("LLMService", "llm-cache-demo")["metadata"][
            "resourceVersion"
        ]

        # SIGKILL: no shutdown hooks, no journal close — the crash case
        mgr = procs[0]
        mgr.kill()
        mgr.wait(timeout=10)

        start_manager(
            procs, subprocess_env, token_file,
            store_port, metrics_port, health_port,
            "--node-ttl", "10", "--data-dir", str(data_dir),
        )

        # The CR is ALREADY there — nothing is re-applied.
        svc = store.get("LLMService", "llm-cache-demo")
        assert svc["spec"]["replicas"] == 3
        assert svc["metadata"]["resourceVersion"] >= rv_before

        wait_until(
            phase_running(store, "llm-cache-demo"), 90,
            "LLMService Running after manager restart",
        )
        svc = store.get("LLMService", "llm-cache-demo")
        assert svc["status"]["availableReplicas"] == 3
        # rv monotonicity across the restart: post-restart reconciles
        # produced HIGHER versions, never a reset counter
        assert svc["metadata"]["resourceVersion"] >= rv_before
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


def test_replica_standby_promotes_with_state(tmp_path, subprocess_env):
    """Store AVAILABILITY, not just durability (r4 verdict missing #1):
    two managers on SEPARATE data-dirs — the primary hosts the store,
    the standby streams its journal (--store-connect + --data-dir).
    ``kill -9`` the primary: the standby binds the shared frontend
    address, wins the election only after the dead leader's REPLICATED
    lease TTL-expires (CAS continuity makes the steal sound), and the
    fleet reconverges WITHOUT anything being re-applied. No shared
    disk anywhere."""
    token_file = tmp_path / "token"
    token_file.write_text("e2e-secret\n")
    dir_a, dir_b = tmp_path / "state-a", tmp_path / "state-b"

    store_port = free_port()  # the shared frontend (VIP role)
    ma_metrics, ma_health = free_port(), free_port()
    mb_metrics, mb_health = free_port(), free_port()
    store_addr = f"http://127.0.0.1:{store_port}"
    procs: list[subprocess.Popen] = []
    try:
        # primary: hosts the store, elects itself (writes the manager
        # lease the standby will have to wait out)
        start_manager(
            procs, subprocess_env, token_file,
            store_port, ma_metrics, ma_health,
            "--node-ttl", "10", "--data-dir", str(dir_a),
            "--leader-elect", "--lease-timings", "2,1,0.3",
        )
        # standby: replica mode — same --store-bind-address (bound only
        # at promotion), own data-dir
        procs.append(subprocess.Popen(
            [
                sys.executable, "-m", "kubeinfer_tpu.manager",
                "--store-bind-address", f"127.0.0.1:{store_port}",
                "--store-connect", store_addr,
                "--data-dir", str(dir_b),
                "--metrics-bind-address", f"127.0.0.1:{mb_metrics}",
                "--health-probe-bind-address", f"127.0.0.1:{mb_health}",
                "--auth-token-file", str(token_file),
                "--tick-interval", "0.2", "--node-ttl", "10",
                "--leader-elect", "--lease-timings", "2,1,0.3",
                "--replica-failover-s", "1.5",
            ],
            env=subprocess_env, cwd=REPO,
        ))
        wait_until(
            lambda: http_get(f"http://127.0.0.1:{mb_health}/healthz")[0] == 200,
            60, "standby /healthz",
        )

        for i in range(2):
            agent_env = dict(subprocess_env)
            agent_env.update(
                NODE_NAME=f"node-{i}",
                STORE_ADDR=store_addr,
                STORE_TOKEN_FILE=str(token_file),
                MODEL_PATH=str(tmp_path / f"models-{i}"),
                GPU_CAPACITY="8",
                GPU_MEMORY="16Gi",
                HEARTBEAT_INTERVAL_S="0.3",
                KUBEINFER_DOWNLOADER="mock",
                LEASE_DURATION_S="2",
                LEASE_RENEW_S="1",
                LEASE_RETRY_S="0.3",
            )
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "kubeinfer_tpu.agent"],
                env=agent_env, cwd=REPO,
            ))

        store = RemoteStore(store_addr, token="e2e-secret")
        wait_until(lambda: len(store.list("Node")) == 2, 60, "2 node heartbeats")
        ctl_apply(SAMPLE, store_addr, token_file, subprocess_env)
        wait_until(
            phase_running(store, "llm-cache-demo"), 90,
            "LLMService phase Running",
        )
        rv_before = store.get("LLMService", "llm-cache-demo")["metadata"][
            "resourceVersion"
        ]
        # the standby's journal tail must be live before the failover
        # drill means anything
        wait_until(
            lambda: http_get(
                f"http://127.0.0.1:{mb_health}/replicaz"
            )[0] == 200,
            60, "standby replica synced",
        )

        # SIGKILL the PRIMARY — the store host. Durability alone cannot
        # save the fleet here: the data-dir dies with the host (we never
        # touch dir_a again).
        primary = procs[0]
        primary.kill()
        primary.wait(timeout=10)

        # the standby detects, binds the frontend, and serves ITS copy
        wait_until(
            lambda: store.healthz(), 60, "standby bound the frontend",
        )
        # full state, nothing re-applied
        svc = store.get("LLMService", "llm-cache-demo")
        assert svc["spec"]["replicas"] == 3
        assert svc["metadata"]["resourceVersion"] >= rv_before
        # election: the standby becomes ready only after stealing the
        # dead leader's replicated lease (TTL 2s)
        wait_until(
            lambda: http_get(
                f"http://127.0.0.1:{mb_health}/readyz"
            )[0] == 200,
            60, "standby elected + reconciling",
        )
        wait_until(
            phase_running(store, "llm-cache-demo"), 90,
            "LLMService Running after promotion",
        )
        svc = store.get("LLMService", "llm-cache-demo")
        assert svc["status"]["availableReplicas"] == 3
        # rv continuity across the promotion: the counter never reset
        # (agent lease CAS-stealing and watch cursors depend on it)
        assert svc["metadata"]["resourceVersion"] >= rv_before
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
