"""Disaggregated prefill/decode: the KV transfer plane end to end.

Layering mirrors the subsystem: wire-format tests are pure numpy
(encode/decode/corruption — every torn-stream mode must surface as
WireError before any page reaches a pool), export-cache tests are pure
LRU bookkeeping, engine tests drive the REAL export capture and import
scatter (the load-bearing checks: an imported prefix must make the
decode token stream byte-identical to a cold local prefill, greedy AND
sampled — the import installs only pool/trie state, so any drift means
the scattered pages differ from what prefill would have written), and
the HTTP/router tests stand up real servers for the two-phase route.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from kubeinfer_tpu.disagg.client import (
    KVFetchError,
    fetch_kv_blocks,
    import_remote_prefix,
)
from kubeinfer_tpu.disagg.export import KVExportCache
from kubeinfer_tpu.disagg.wire import (
    KVBlockPayload,
    WireError,
    decode_payload,
    encode_payload,
)
from kubeinfer_tpu.inference import PRESETS, init_params
from kubeinfer_tpu.inference.batching import ContinuousEngine
from kubeinfer_tpu.inference.engine import Engine
from kubeinfer_tpu.inference.kv_blocks import prefix_fingerprints
from kubeinfer_tpu.inference.server import InferenceServer
from kubeinfer_tpu.router import FleetRouter, RouterServer

TINY = PRESETS["tiny"]
BS = 16  # block size shared by every engine here


@pytest.fixture(scope="module")
def params():
    return init_params(TINY, jax.random.PRNGKey(0))


def mk_engine(params, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("cache_len", 128)
    kw.setdefault("block_size", BS)
    return ContinuousEngine(params, TINY, **kw).start()


def prompt_tokens(n, seed=11):
    rng = np.random.default_rng(seed)
    return rng.integers(0, TINY.vocab_size, size=n).tolist()


def _pages(blocks=3, layers=2, n_kv=2, d=8, dtype=np.float32, seed=0):
    rng = np.random.default_rng(seed)
    shape = (layers, blocks, 4, n_kv, d)
    k = rng.standard_normal(shape).astype(dtype)
    v = rng.standard_normal(shape).astype(dtype)
    return k, v


class TestWire:
    def test_round_trip_float32(self):
        k, v = _pages()
        fps = [10, 20, 30]
        blob = encode_payload(k, v, fps, block_size=4)
        p = decode_payload(blob)
        assert isinstance(p, KVBlockPayload)
        assert np.array_equal(p.pages_k, k)
        assert np.array_equal(p.pages_v, v)
        assert p.fingerprints == (10, 20, 30)
        assert p.block_size == 4
        assert p.blocks == 3
        assert p.byte_size == k.nbytes + v.nbytes

    def test_round_trip_bfloat16(self):
        ml_dtypes = pytest.importorskip("ml_dtypes")
        k, v = _pages(dtype=ml_dtypes.bfloat16)
        blob = encode_payload(k, v, [1, 2, 3], block_size=4)
        p = decode_payload(blob)
        assert p.pages_k.dtype == np.dtype(ml_dtypes.bfloat16)
        assert np.array_equal(p.pages_k, k)

    def test_body_corruption_fails_checksum(self):
        k, v = _pages()
        blob = bytearray(encode_payload(k, v, [1, 2, 3], block_size=4))
        blob[-10] ^= 0x01  # one flipped bit deep in the V pages
        with pytest.raises(WireError, match="checksum"):
            decode_payload(bytes(blob))

    def test_truncated_body_detected_before_checksum(self):
        k, v = _pages()
        blob = encode_payload(k, v, [1, 2, 3], block_size=4)
        with pytest.raises(WireError, match="truncated"):
            decode_payload(blob[:-5])

    def test_bad_magic_and_missing_header(self):
        with pytest.raises(WireError):
            decode_payload(b'{"magic": "nope"}\nxxxx')
        with pytest.raises(WireError, match="header"):
            decode_payload(b"no newline anywhere")

    def test_encode_validates_shape_agreement(self):
        k, v = _pages()
        with pytest.raises(WireError, match="fingerprints"):
            encode_payload(k, v, [1, 2], block_size=4)  # 3 blocks
        with pytest.raises(WireError, match="disagree"):
            encode_payload(k, v[:, :2], [1, 2, 3], block_size=4)
        with pytest.raises(WireError, match="layers"):
            encode_payload(k[0], v[0], [1, 2, 3], block_size=4)

    def test_round_trip_v2_int8(self):
        k, v = _pages(dtype=np.int8)
        rng = np.random.default_rng(3)
        sk = rng.random((2, 3, 2)).astype(np.float32)
        sv = rng.random((2, 3, 2)).astype(np.float32)
        blob = encode_payload(k, v, [1, 2, 3], block_size=4,
                              scales_k=sk, scales_v=sv, kv_dtype="int8")
        assert blob.split(b"\n", 1)[0].startswith(
            b'{"magic": "kubeinfer-kvwire/2"'
        )
        p = decode_payload(blob)
        assert p.kv_dtype == "int8"
        assert np.array_equal(p.pages_k, k)
        assert np.array_equal(p.scales_k, sk)
        assert np.array_equal(p.scales_v, sv)
        assert p.byte_size == k.nbytes + v.nbytes + sk.nbytes + sv.nbytes

    def test_bf16_export_stays_v1_byte_identical(self):
        # a pre-quantization fleet must see the exact v1 bytes it
        # always did — the v2 magic appears only when scales do
        k, v = _pages()
        blob = encode_payload(k, v, [1, 2, 3], block_size=4)
        assert blob.split(b"\n", 1)[0].startswith(
            b'{"magic": "kubeinfer-kvwire/1"'
        )
        assert b"kv_dtype" not in blob.split(b"\n", 1)[0]
        p = decode_payload(blob)
        assert p.kv_dtype == "bf16" and p.scales_k is None

    def test_v2_scale_corruption_fails_checksum(self):
        k, v = _pages(dtype=np.int8)
        sk = np.ones((2, 3, 2), np.float32)
        blob = bytearray(encode_payload(
            k, v, [1, 2, 3], block_size=4,
            scales_k=sk, scales_v=sk, kv_dtype="int8",
        ))
        blob[-3] ^= 0x10  # deep in the V scales
        with pytest.raises(WireError, match="checksum"):
            decode_payload(bytes(blob))

    def test_encode_validates_dtype_scale_agreement(self):
        k, v = _pages(dtype=np.int8)
        sk = np.ones((2, 3, 2), np.float32)
        with pytest.raises(WireError, match="together"):
            encode_payload(k, v, [1, 2, 3], block_size=4, scales_k=sk,
                           kv_dtype="int8")
        with pytest.raises(WireError, match="inconsistent"):
            encode_payload(k, v, [1, 2, 3], block_size=4,
                           kv_dtype="int8")
        with pytest.raises(WireError, match="inconsistent"):
            encode_payload(k, v, [1, 2, 3], block_size=4,
                           scales_k=sk, scales_v=sk)
        with pytest.raises(WireError, match="float32"):
            encode_payload(k, v, [1, 2, 3], block_size=4,
                           scales_k=sk.astype(np.float64),
                           scales_v=sk, kv_dtype="int8")

    def test_v2_header_claiming_bf16_rejected(self):
        # a forged v2 header downgrading kv_dtype would make the body
        # length check pass against phantom scale bytes — refuse it at
        # the header parse
        k, v = _pages(dtype=np.int8)
        sk = np.ones((2, 3, 2), np.float32)
        blob = encode_payload(k, v, [1, 2, 3], block_size=4,
                              scales_k=sk, scales_v=sk, kv_dtype="int8")
        nl = blob.find(b"\n")
        hdr = json.loads(blob[:nl])
        hdr["kv_dtype"] = "bf16"
        with pytest.raises(WireError, match="bf16"):
            decode_payload(json.dumps(hdr).encode() + blob[nl:])

    def test_header_shape_inconsistency_detected(self):
        # a header claiming a different block count than its body
        # implies must fail on the implied-size check, not reshape junk
        k, v = _pages()
        blob = encode_payload(k, v, [1, 2, 3], block_size=4)
        nl = blob.find(b"\n")
        hdr = json.loads(blob[:nl])
        hdr["blocks"] = 2
        hdr["fingerprints"] = [1, 2]
        forged = json.dumps(hdr).encode() + blob[nl:]
        with pytest.raises(WireError):
            decode_payload(forged)


class TestExportCache:
    def test_lru_eviction_and_touch(self):
        c = KVExportCache(capacity=2)
        c.put(1, b"one")
        c.put(2, b"two")
        assert c.get(1) == b"one"  # touches 1: now 2 is LRU-oldest
        c.put(3, b"three")
        assert c.get(2) is None
        assert c.get(1) == b"one" and c.get(3) == b"three"
        s = c.stats()
        assert s["evictions"] == 1 and s["entries"] == 2
        assert s["hits"] == 3 and s["misses"] == 1

    def test_put_same_key_replaces_without_eviction(self):
        c = KVExportCache(capacity=2)
        c.put(1, b"a")
        c.put(1, b"b")
        assert len(c) == 1 and c.get(1) == b"b"
        assert c.stats()["evictions"] == 0

    def test_capacity_validation(self):
        with pytest.raises(ValueError):
            KVExportCache(capacity=0)


class TestEngineImport:
    def test_prefill_only_export_capture(self, params):
        eng = mk_engine(params)
        try:
            p = prompt_tokens(70)
            req = eng.serve(p, max_new_tokens=0, eos_id=-1,
                            export_kv=True)
            # prefill-only retires with zero generated tokens but a
            # captured export of every full prompt block
            assert req.out_tokens == []
            exp = req.kv_export
            assert exp is not None
            assert exp["block_size"] == BS
            assert exp["fingerprints"] == prefix_fingerprints(p, BS)
            n = len(p) // BS
            assert exp["pages_k"].shape[1] == n
            assert exp["pages_k"].shape == exp["pages_v"].shape
            # capture must not leak the walk's references: all export
            # blocks are trie-held only (evictable) afterwards
            assert eng.kv_cache_stats()["blocks_in_use"] == n
        finally:
            eng.stop()

    def test_no_export_without_flag_or_full_block(self, params):
        eng = mk_engine(params)
        try:
            req = eng.serve(prompt_tokens(40), max_new_tokens=0,
                            eos_id=-1)
            assert req.kv_export is None  # flag off
            req = eng.serve(prompt_tokens(BS - 1, seed=5),
                            max_new_tokens=0, eos_id=-1, export_kv=True)
            assert req.kv_export is None  # no full block to export
        finally:
            eng.stop()

    def test_import_parity_greedy_and_sampled(self, params):
        """THE disaggregation contract: decode over imported blocks is
        byte-identical to decode over a local cold prefill."""
        p = prompt_tokens(70)
        ref = mk_engine(params)
        ref_g = ref.generate(p, max_new_tokens=6, eos_id=-1)
        ref_s = ref.generate(p, max_new_tokens=6, eos_id=-1,
                             temperature=0.8, seed=123)
        ref.stop()

        a = mk_engine(params)
        exp = a.serve(p, max_new_tokens=0, eos_id=-1,
                      export_kv=True).kv_export
        a.stop()
        payload = decode_payload(encode_payload(
            exp["pages_k"], exp["pages_v"], exp["fingerprints"],
            exp["block_size"],
        ))

        b = mk_engine(params)
        try:
            fps = prefix_fingerprints(p, BS)
            n, reason = b.import_prefix(
                p[:len(fps) * BS], payload.pages_k, payload.pages_v,
            )
            assert (n, reason) == (len(fps), None)
            assert b.imports_total == 1
            assert b.imported_blocks_total == len(fps)
            # the decode side recomputes at least the final prompt
            # token (committed-blocks rule) but NO imported block
            hits_before = b.kv_cache_stats()["hits"]
            assert b.generate(p, max_new_tokens=6, eos_id=-1) == ref_g
            assert b.kv_cache_stats()["hits"] == hits_before + 1
            assert b.generate(p, max_new_tokens=6, eos_id=-1,
                              temperature=0.8, seed=123) == ref_s
        finally:
            b.stop()

    def test_duplicate_import_dedups(self, params):
        p = prompt_tokens(70)
        a = mk_engine(params)
        exp = a.serve(p, max_new_tokens=0, eos_id=-1,
                      export_kv=True).kv_export
        a.stop()
        b = mk_engine(params)
        try:
            fps = prefix_fingerprints(p, BS)
            toks = p[:len(fps) * BS]
            for _ in range(2):
                n, reason = b.import_prefix(
                    toks, exp["pages_k"], exp["pages_v"],
                )
                assert (n, reason) == (len(fps), None)
            # second import found every node cached: its fresh blocks
            # freed right back, so occupancy is one copy, not two
            assert b.kv_cache_stats()["blocks_in_use"] == len(fps)
        finally:
            b.stop()

    def test_import_rejects_bad_shapes(self, params):
        eng = mk_engine(params)
        try:
            k, v = _pages(blocks=2, layers=2, n_kv=2, d=8)
            # wrong page geometry for this engine
            n, reason = eng.import_prefix(list(range(2 * BS)), k, v)
            assert n == 0 and reason == "shape_mismatch"
            # token count disagreeing with block count
            exp_shape = (TINY.num_hidden_layers, 1, BS,
                         TINY.num_key_value_heads, TINY.head_dim)
            kk = np.zeros(exp_shape, np.float32)
            n, reason = eng.import_prefix(list(range(3)), kk, kk)
            assert n == 0 and reason == "shape_mismatch"
        finally:
            eng.stop()

    def test_int8_export_import_parity(self, params):
        """The disaggregation contract under quantization: decode over
        imported int8 pages + scales is token-identical to the int8
        engine's own cold prefill (NOT to bf16 — the int8 path is
        tolerance-pinned against bf16, but exact against itself)."""
        p = prompt_tokens(70)
        ref = mk_engine(params, kv_dtype="int8")
        ref_g = ref.generate(p, max_new_tokens=6, eos_id=-1)
        ref.stop()

        a = mk_engine(params, kv_dtype="int8")
        exp = a.serve(p, max_new_tokens=0, eos_id=-1,
                      export_kv=True).kv_export
        a.stop()
        assert exp["kv_dtype"] == "int8"
        assert exp["pages_k"].dtype == np.int8
        payload = decode_payload(encode_payload(
            exp["pages_k"], exp["pages_v"], exp["fingerprints"],
            exp["block_size"], scales_k=exp["scales_k"],
            scales_v=exp["scales_v"], kv_dtype="int8",
        ))

        b = mk_engine(params, kv_dtype="int8")
        try:
            fps = prefix_fingerprints(p, BS)
            n, reason = b.import_prefix(
                p[:len(fps) * BS], payload.pages_k, payload.pages_v,
                scales_k=payload.scales_k, scales_v=payload.scales_v,
                kv_dtype="int8",
            )
            assert (n, reason) == (len(fps), None)
            assert b.generate(p, max_new_tokens=6, eos_id=-1) == ref_g
        finally:
            b.stop()

    def test_import_rejects_kv_dtype_mismatch(self, params):
        # both directions: a bf16 blob must not scatter into an int8
        # pool (its pages would be reinterpreted as quantized) and an
        # int8 blob must not scatter into a bf16 pool
        p = list(range(BS))
        int8_eng = mk_engine(params, kv_dtype="int8")
        try:
            exp_shape = (TINY.num_hidden_layers, 1, BS,
                         TINY.num_key_value_heads, TINY.head_dim)
            kk = np.zeros(exp_shape, np.float32)
            n, reason = int8_eng.import_prefix(p, kk, kk)
            assert (n, reason) == (0, "kv_dtype_mismatch")
        finally:
            int8_eng.stop()
        bf16_eng = mk_engine(params)
        try:
            exp_shape = (TINY.num_hidden_layers, 1, BS,
                         TINY.num_key_value_heads, TINY.head_dim)
            kq = np.zeros(exp_shape, np.int8)
            sc = np.ones((TINY.num_hidden_layers, 1,
                          TINY.num_key_value_heads), np.float32)
            n, reason = bf16_eng.import_prefix(
                p, kq, kq, scales_k=sc, scales_v=sc, kv_dtype="int8",
            )
            assert (n, reason) == (0, "kv_dtype_mismatch")
        finally:
            bf16_eng.stop()


class TestClient:
    def test_fetch_unreachable_is_fetch_error(self, params):
        eng = mk_engine(params)
        try:
            n, reason, _ = import_remote_prefix(
                eng, prompt_tokens(40), "http://127.0.0.1:9",
                timeout_s=0.5,
            )
            assert n == 0 and reason == "fetch_error"
            with pytest.raises(KVFetchError):
                fetch_kv_blocks("http://127.0.0.1:9", 1, timeout_s=0.5)
        finally:
            eng.stop()

    def test_sub_block_prompt_short_circuits(self, params):
        eng = mk_engine(params)
        try:
            n, reason, nbytes = import_remote_prefix(
                eng, prompt_tokens(BS - 1), "http://127.0.0.1:9",
            )
            assert (n, reason, nbytes) == (0, "no_full_block", 0)
        finally:
            eng.stop()

    def test_wire_v1_blob_rejected_by_int8_importer(self, params,
                                                    monkeypatch):
        """Mixed-fleet regression: a pre-quantization (wire v1, bf16)
        prefill replica answering an int8 decode replica must degrade
        to local prefill with the kv_dtype_mismatch fallback reason —
        never scatter bf16 bytes into the quantized pool, and never
        misreport the valid v1 blob as a wire error."""
        p = prompt_tokens(70)
        a = mk_engine(params)  # bf16 exporter -> v1 on the wire
        exp = a.serve(p, max_new_tokens=0, eos_id=-1,
                      export_kv=True).kv_export
        a.stop()
        blob = encode_payload(exp["pages_k"], exp["pages_v"],
                              exp["fingerprints"], exp["block_size"])
        assert blob.split(b"\n", 1)[0].startswith(
            b'{"magic": "kubeinfer-kvwire/1"'
        )

        import kubeinfer_tpu.disagg.client as client_mod

        monkeypatch.setattr(
            client_mod, "fetch_kv_blocks",
            lambda *a, **kw: decode_payload(blob),
        )
        eng = mk_engine(params, kv_dtype="int8")
        try:
            n, reason, nbytes = import_remote_prefix(
                eng, p, "http://unused",
            )
            assert (n, reason) == (0, "kv_dtype_mismatch")
            assert nbytes > 0  # the blob was fetched and decoded fine
            assert eng.imports_total == 0  # never reached the engine
        finally:
            eng.stop()


@pytest.mark.slow
class TestServerEndpoints:
    @pytest.fixture(scope="class")
    def fleet(self, params):
        servers = []
        for name in ("pre", "dec"):
            cont = mk_engine(params)
            srv = InferenceServer(
                Engine(params, TINY), model_id=name, port=0,
                continuous=cont,
            ).start()
            servers.append((srv, cont))
        yield servers
        for srv, cont in servers:
            srv.stop()
            cont.stop()

    def _post(self, port, body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/completions",
            data=json.dumps(body).encode(), method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.loads(r.read())

    def test_prefill_only_then_kv_blocks_fetch(self, fleet):
        (pre, pre_cont), _ = fleet
        p = prompt_tokens(70, seed=21)
        status, doc = self._post(pre.port, {
            "prompt": p, "max_tokens": 0,
        })
        assert status == 200
        assert doc["kubeinfer"]["route"] == "prefill"
        assert doc["usage"]["completion_tokens"] == 0
        ext = doc["kubeinfer"]["kv_export"]
        fps = prefix_fingerprints(p, BS)
        assert ext["fingerprint"] == fps[-1]
        assert ext["blocks"] == len(fps)
        # the wire blob round-trips through the endpoint
        payload = fetch_kv_blocks(
            f"http://127.0.0.1:{pre.port}", fps[-1],
        )
        assert list(payload.fingerprints) == fps
        # export-direction metrics materialized
        with urllib.request.urlopen(
            f"http://127.0.0.1:{pre.port}/metrics", timeout=10
        ) as r:
            body = r.read().decode()
        assert 'kubeinfer_kv_stream_blocks_total{direction="export"}' \
            in body

    def test_kv_blocks_miss_and_bad_query(self, fleet):
        (pre, _), _ = fleet
        for q, code in (("fp=424242", 404), ("fp=wat", 400), ("", 400)):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{pre.port}/kv/blocks?{q}",
                    timeout=10,
                )
            assert ei.value.code == code

    def test_kv_source_hook_imports_and_serves_parity(self, fleet,
                                                      params):
        (pre, _), (dec, dec_cont) = fleet
        p = prompt_tokens(70, seed=22)
        ref = mk_engine(params)
        expect = ref.generate(p, max_new_tokens=5, eos_id=-1)
        ref.stop()
        self._post(pre.port, {"prompt": p, "max_tokens": 0})
        imports_before = dec_cont.imports_total
        status, doc = self._post(dec.port, {
            "prompt": p, "max_tokens": 5,
            "kubeinfer_kv_source": f"http://127.0.0.1:{pre.port}",
        })
        assert status == 200
        assert doc["choices"][0]["tokens"] == expect
        assert dec_cont.imports_total == imports_before + 1
        # a locally-warm repeat must skip the fetch entirely
        status, doc = self._post(dec.port, {
            "prompt": p, "max_tokens": 5,
            "kubeinfer_kv_source": f"http://127.0.0.1:{pre.port}",
        })
        assert doc["choices"][0]["tokens"] == expect
        assert dec_cont.imports_total == imports_before + 1

    def test_kv_source_unreachable_falls_back_locally(self, fleet,
                                                      params):
        _, (dec, dec_cont) = fleet
        p = prompt_tokens(70, seed=23)
        ref = mk_engine(params)
        expect = ref.generate(p, max_new_tokens=4, eos_id=-1)
        ref.stop()
        status, doc = self._post(dec.port, {
            "prompt": p, "max_tokens": 4,
            "kubeinfer_kv_source": "http://127.0.0.1:9",
        })
        assert status == 200
        assert doc["choices"][0]["tokens"] == expect
        assert dec.metrics["disagg_fallbacks"].value("fetch_error") > 0

    def test_stale_export_fingerprint_chain_guard(self, fleet, params):
        """A stale/colliding export must be rejected by the full-chain
        compare, never scattered: plant a blob for OTHER tokens under
        OUR deepest fingerprint and watch the import refuse it."""
        (pre, _), _ = fleet
        ours = prompt_tokens(70, seed=24)
        theirs = prompt_tokens(70, seed=25)
        a = mk_engine(params)
        exp = a.serve(theirs, max_new_tokens=0, eos_id=-1,
                      export_kv=True).kv_export
        a.stop()
        blob = encode_payload(exp["pages_k"], exp["pages_v"],
                              exp["fingerprints"], exp["block_size"])
        our_fps = prefix_fingerprints(ours, BS)
        pre.kv_exports.put(our_fps[-1], blob)
        b = mk_engine(params)
        try:
            n, reason, _ = import_remote_prefix(
                b, ours, f"http://127.0.0.1:{pre.port}",
            )
            assert n == 0 and reason == "fingerprint_mismatch"
            assert b.imports_total == 0
        finally:
            b.stop()


@pytest.mark.slow
class TestRouterTwoPhase:
    def test_two_phase_route_is_token_identical(self, params):
        p = prompt_tokens(70, seed=31)
        short = prompt_tokens(20, seed=32)
        ref = mk_engine(params)
        expect = ref.generate(p, max_new_tokens=5, eos_id=-1)
        expect_s = ref.generate(p, max_new_tokens=5, eos_id=-1,
                                temperature=0.7, seed=9)
        expect_short = ref.generate(short, max_new_tokens=3, eos_id=-1)
        ref.stop()

        servers = {}
        for name in ("prefill0", "decode0", "decode1"):
            cont = mk_engine(params)
            srv = InferenceServer(
                Engine(params, TINY), model_id=name, port=0,
                continuous=cont,
            ).start()
            servers[name] = (srv, cont)
        router = FleetRouter()
        for name in ("decode0", "decode1"):
            router.add_replica(
                name, f"http://127.0.0.1:{servers[name][0].port}")
        router.add_prefill_replica(
            "prefill0", f"http://127.0.0.1:{servers['prefill0'][0].port}")
        rs = RouterServer(router, port=0, prefill_threshold=64)
        rs.poll_once()
        rs.start(poll=False)
        try:
            def forward(body):
                code, payload = rs.forward(json.dumps(body).encode())
                return code, json.loads(payload)

            code, doc = forward({"prompt": p, "max_tokens": 5})
            assert code == 200
            assert doc["choices"][0]["tokens"] == expect
            # the prefill tier did the prefill; exactly one decode
            # replica imported the blocks
            assert len(servers["prefill0"][0].kv_exports) >= 1
            imports = sum(servers[n][1].imports_total
                          for n in ("decode0", "decode1"))
            assert imports == 1
            assert router.metrics["prefill_routed"].value("prefill0") \
                == 1

            # sampled rides the same plane, same identity
            code, doc = forward({"prompt": p, "max_tokens": 5,
                                 "temperature": 0.7, "seed": 9})
            assert code == 200
            assert doc["choices"][0]["tokens"] == expect_s

            # short prompts bypass the prefill tier entirely
            before = router.metrics["prefill_routed"].value("prefill0")
            code, doc = forward({"prompt": short, "max_tokens": 3})
            assert code == 200
            assert doc["choices"][0]["tokens"] == expect_short
            assert router.metrics["prefill_routed"].value("prefill0") \
                == before

            # the prefill tier never served a completion
            for outcome in ("ok",):
                assert router.metrics["requests"].value(
                    "prefill0", outcome) == 0
            snap = rs.replica_snapshot()
            assert {v["name"]: v["role"] for v in snap} == {
                "decode0": "decode", "decode1": "decode",
                "prefill0": "prefill",
            }
        finally:
            rs.stop()
            for srv, cont in servers.values():
                srv.stop()
                cont.stop()
