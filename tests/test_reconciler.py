"""Batched reconciler behavior.

Covers the reference controller's contract (create-if-missing, status sync
— llmservice_controller_test.go had only a no-error smoke test) plus the
gaps this build fixes: drift correction, GC, explicit solver placement,
preemption under churn.
"""

import numpy as np

from kubeinfer_tpu.api.types import LLMService, LLMServiceSpec, SchedulerPolicy
from kubeinfer_tpu.api.workload import NodeState, Workload
from kubeinfer_tpu.controller import Controller
from kubeinfer_tpu.controlplane import Store
from kubeinfer_tpu.metrics import (
    REGISTRY,
    evacuations_total,
    reconcile_total,
)
from kubeinfer_tpu.utils.clock import SimulatedClock


def mk_service(name="svc", replicas=2, gpu=1, policy="jax-greedy", **spec_over):
    svc = LLMService()
    svc.metadata.name = name
    svc.spec = LLMServiceSpec(
        model=f"org/{name}-model",
        replicas=replicas,
        gpu_per_replica=gpu,
        scheduler_policy=SchedulerPolicy(policy),
        **spec_over,
    )
    svc.validate()
    return svc


def mk_node(name, gpu=8, mem_gib=64, cached=(), heartbeat=0.0, serving=None):
    n = NodeState(
        gpu_capacity=gpu,
        gpu_free=gpu,
        gpu_memory_bytes=int(mem_gib * 2**30),
        gpu_memory_free_bytes=int(mem_gib * 2**30),
        cached_models=list(cached),
        heartbeat=heartbeat,
        serving_stats=dict(serving or {}),
    )
    n.metadata.name = name
    return n


def setup(n_nodes=3, **node_kw):
    store = Store()
    clock = SimulatedClock(start=100.0)
    for i in range(n_nodes):
        store.create(NodeState.KIND, mk_node(f"node-{i}", **node_kw).to_dict())
    return store, clock, Controller(store, clock=clock)


class TestWorkloadLifecycle:
    def test_creates_workload_with_env_contract(self):
        store, clock, c = setup()
        store.create(LLMService.KIND, mk_service("svc").to_dict())
        res = c.reconcile_once()
        assert res.workloads_created == 1
        w = Workload.from_dict(store.get(Workload.KIND, "svc"))
        # env parity with reference llmservice_controller.go:231-266
        assert w.env["CONFIGMAP_NAME"] == "svc-cache"
        assert w.env["MODEL_REPO"] == "org/svc-model"
        assert w.env["MODEL_PATH"] == "/models"
        assert w.cache_group == "svc-cache"
        assert len(w.replicas) == 2

    def test_replica_scale_up_and_down(self):
        store, clock, c = setup()
        store.create(LLMService.KIND, mk_service("svc", replicas=2).to_dict())
        c.reconcile_once()

        svc = LLMService.from_dict(store.get(LLMService.KIND, "svc"))
        svc.spec.replicas = 5
        store.update(LLMService.KIND, svc.to_dict())
        c.reconcile_once()
        assert len(Workload.from_dict(store.get(Workload.KIND, "svc")).replicas) == 5

        svc = LLMService.from_dict(store.get(LLMService.KIND, "svc"))
        svc.spec.replicas = 1
        store.update(LLMService.KIND, svc.to_dict())
        c.reconcile_once()
        assert len(Workload.from_dict(store.get(Workload.KIND, "svc")).replicas) == 1

    def test_model_change_restarts_replicas(self):
        store, clock, c = setup()
        store.create(LLMService.KIND, mk_service("svc").to_dict())
        c.reconcile_once()
        # simulate agent bringing replicas up
        w = Workload.from_dict(store.get(Workload.KIND, "svc"))
        for r in w.replicas:
            r.phase = "Ready"
        store.update(Workload.KIND, w.to_dict())

        svc = LLMService.from_dict(store.get(LLMService.KIND, "svc"))
        svc.spec.model = "org/new-model"
        store.update(LLMService.KIND, svc.to_dict())
        c.reconcile_once()
        w = Workload.from_dict(store.get(Workload.KIND, "svc"))
        assert w.model_repo == "org/new-model"
        assert all(r.phase in ("Starting", "Pending") for r in w.replicas)

    def test_deleted_service_garbage_collects_workload(self):
        store, clock, c = setup()
        store.create(LLMService.KIND, mk_service("svc").to_dict())
        c.reconcile_once()
        store.delete(LLMService.KIND, "svc")
        res = c.reconcile_once()
        assert res.workloads_deleted == 1
        assert store.list(Workload.KIND) == []

    def test_workload_recreated_if_deleted(self):
        """Owns semantics: a deleted owned object is re-created
        (llmservice_controller.go:316-320 + 111-129)."""
        store, clock, c = setup()
        store.create(LLMService.KIND, mk_service("svc").to_dict())
        c.reconcile_once()
        store.delete(Workload.KIND, "svc")
        res = c.reconcile_once()
        assert res.workloads_created == 1
        assert store.get(Workload.KIND, "svc")


class TestPlacement:
    def test_all_replicas_bound_when_capacity_exists(self):
        store, clock, c = setup(n_nodes=2)
        store.create(LLMService.KIND, mk_service("svc", replicas=4).to_dict())
        res = c.reconcile_once()
        assert res.replicas_placed == 4
        w = Workload.from_dict(store.get(Workload.KIND, "svc"))
        assert all(r.node.startswith("node-") for r in w.replicas)
        assert all(r.phase == "Starting" for r in w.replicas)

    def test_no_nodes_leaves_pending(self):
        store, clock, c = setup(n_nodes=0)
        store.create(LLMService.KIND, mk_service("svc").to_dict())
        res = c.reconcile_once()
        assert res.replicas_placed == 0
        svc = LLMService.from_dict(store.get(LLMService.KIND, "svc"))
        assert svc.status.phase == "Pending"

    def test_stale_node_excluded(self):
        store, clock, c = setup(n_nodes=0)
        store.create(NodeState.KIND, mk_node("fresh", heartbeat=95.0).to_dict())
        store.create(NodeState.KIND, mk_node("stale", heartbeat=10.0).to_dict())
        store.create(LLMService.KIND, mk_service("svc", replicas=2).to_dict())
        res = c.reconcile_once()
        assert res.nodes == 1
        w = Workload.from_dict(store.get(Workload.KIND, "svc"))
        assert all(r.node == "fresh" for r in w.replicas)

    def test_queue_pressure_gates_cache_affinity(self):
        """Two nodes both advertise the model cached, but one's serving
        replica is drowning (queue >= PRESSURE_AFFINITY_CUTOFF per
        slot): its cache-affinity bit is gated off, so the idle cached
        node is strictly preferred — placement stops feeding a node at
        the same threshold the fleet router stops routing to it."""
        store, clock, c = setup(n_nodes=0)
        model = "org/svc-model"
        store.create(NodeState.KIND, mk_node(
            "node-hot", cached=(model,), heartbeat=95.0,
            serving={"queue_depth": 8, "n_slots": 2},
        ).to_dict())
        store.create(NodeState.KIND, mk_node(
            "node-idle", cached=(model,), heartbeat=95.0,
            serving={"queue_depth": 0, "n_slots": 2},
        ).to_dict())
        store.create(LLMService.KIND, mk_service("svc", replicas=1).to_dict())
        res = c.reconcile_once()
        assert res.replicas_placed == 1
        w = Workload.from_dict(store.get(Workload.KIND, "svc"))
        assert w.replicas[0].node == "node-idle"

    def test_capacity_respected_across_services(self):
        store, clock, c = setup(n_nodes=1, gpu=4)
        store.create(LLMService.KIND, mk_service("a", replicas=3, gpu=2).to_dict())
        store.create(LLMService.KIND, mk_service("b", replicas=3, gpu=2).to_dict())
        res = c.reconcile_once()
        assert res.replicas_placed == 2  # 4 chips / 2 per replica

    def test_priority_preempts_on_rescheduling(self):
        """Config 4: a higher-priority service arriving later displaces a
        lower-priority incumbent when capacity is scarce."""
        store, clock, c = setup(n_nodes=1, gpu=2)
        store.create(
            LLMService.KIND, mk_service("low", replicas=1, gpu=2, priority=0).to_dict()
        )
        c.reconcile_once()
        w_low = Workload.from_dict(store.get(Workload.KIND, "low"))
        assert w_low.replicas[0].node == "node-0"

        store.create(
            LLMService.KIND,
            mk_service("high", replicas=1, gpu=2, priority=10).to_dict(),
        )
        c.reconcile_once()
        w_low = Workload.from_dict(store.get(Workload.KIND, "low"))
        w_high = Workload.from_dict(store.get(Workload.KIND, "high"))
        assert w_high.replicas[0].node == "node-0"
        assert w_low.replicas[0].node == ""
        assert w_low.replicas[0].phase == "Pending"

    def test_hysteresis_keeps_placement_stable_across_ticks(self):
        store, clock, c = setup(n_nodes=4)
        store.create(LLMService.KIND, mk_service("svc", replicas=4).to_dict())
        c.reconcile_once()
        first = [
            r.node
            for r in Workload.from_dict(store.get(Workload.KIND, "svc")).replicas
        ]
        for _ in range(3):
            c.reconcile_once()
        after = [
            r.node
            for r in Workload.from_dict(store.get(Workload.KIND, "svc")).replicas
        ]
        assert first == after

    def test_gang_all_or_nothing_across_reconcile(self):
        store, clock, c = setup(n_nodes=1, gpu=4)
        store.create(
            LLMService.KIND,
            mk_service("gang", replicas=3, gpu=2, gang=True).to_dict(),
        )
        res = c.reconcile_once()
        assert res.replicas_placed == 0  # needs 6 chips, node has 4
        svc = LLMService.from_dict(store.get(LLMService.KIND, "gang"))
        assert svc.status.phase == "Pending"

    def test_native_policy_places_too(self):
        store, clock, c = setup(n_nodes=2)
        store.create(
            LLMService.KIND,
            mk_service("svc", replicas=3, policy="native-greedy").to_dict(),
        )
        res = c.reconcile_once()
        assert res.replicas_placed == 3
        assert "native-greedy" in res.solve_ms


class TestStatus:
    def test_status_phases_progress(self):
        store, clock, c = setup()
        store.create(LLMService.KIND, mk_service("svc", replicas=2).to_dict())
        c.reconcile_once()
        svc = LLMService.from_dict(store.get(LLMService.KIND, "svc"))
        assert svc.status.phase == "Scheduling"

        # agent marks one Ready
        w = Workload.from_dict(store.get(Workload.KIND, "svc"))
        w.replicas[0].phase = "Ready"
        store.update(Workload.KIND, w.to_dict())
        c.reconcile_once()
        svc = LLMService.from_dict(store.get(LLMService.KIND, "svc"))
        assert svc.status.phase == "Degraded"
        assert svc.status.available_replicas == 1

        w = Workload.from_dict(store.get(Workload.KIND, "svc"))
        for r in w.replicas:
            r.phase = "Ready"
        store.update(Workload.KIND, w.to_dict())
        c.reconcile_once()
        svc = LLMService.from_dict(store.get(LLMService.KIND, "svc"))
        assert svc.status.phase == "Running"
        assert svc.status.get_condition("Available").status == "True"
        assert svc.status.placements and all(svc.status.placements)

    def test_cache_coordinator_from_lease(self):
        store, clock, c = setup()
        store.create(LLMService.KIND, mk_service("svc").to_dict())
        store.create(
            "Lease",
            {
                "metadata": {"name": "svc-cache-lease"},
                "spec": {"holderIdentity": "svc-pod-1"},
            },
        )
        c.reconcile_once()
        svc = LLMService.from_dict(store.get(LLMService.KIND, "svc"))
        assert svc.status.cache_coordinator == "svc-pod-1"

    def test_reconcile_metrics_recorded(self):
        REGISTRY.reset()
        store, clock, c = setup()
        store.create(LLMService.KIND, mk_service("svc").to_dict())
        c.reconcile_once()
        assert reconcile_total.value("llmservice", "success") == 1
        rendered = REGISTRY.render()
        assert "kubeinfer_solve_duration_seconds_bucket" in rendered
        assert 'kubeinfer_llmservice_total 1' in rendered


class TestCrossPolicyCapacity:
    def test_policy_groups_do_not_double_book(self):
        """Regression: each policy group's solve must see capacity already
        consumed by other groups' placements in the same tick."""
        store, clock, c = setup(n_nodes=1, gpu=8)
        store.create(
            LLMService.KIND,
            mk_service("a", replicas=2, gpu=3, policy="jax-greedy").to_dict(),
        )
        store.create(
            LLMService.KIND,
            mk_service("b", replicas=2, gpu=3, policy="native-greedy").to_dict(),
        )
        res = c.reconcile_once()
        assert res.replicas_placed == 2  # 8 chips / 3 per replica = 2 fit
        total_gpu = 0
        for name in ("a", "b"):
            w = Workload.from_dict(store.get(Workload.KIND, name))
            total_gpu += sum(3 for r in w.replicas if r.node)
        assert total_gpu <= 8

    def test_high_priority_group_solves_first(self):
        store, clock, c = setup(n_nodes=1, gpu=4)
        store.create(
            LLMService.KIND,
            mk_service("low", replicas=1, gpu=4, policy="jax-greedy",
                       priority=0).to_dict(),
        )
        store.create(
            LLMService.KIND,
            mk_service("high", replicas=1, gpu=4, policy="native-greedy",
                       priority=50).to_dict(),
        )
        c.reconcile_once()
        w_high = Workload.from_dict(store.get(Workload.KIND, "high"))
        w_low = Workload.from_dict(store.get(Workload.KIND, "low"))
        assert w_high.replicas[0].node == "node-0"
        assert w_low.replicas[0].node == ""


def set_serving(store, name, serving):
    n = NodeState.from_dict(store.get(NodeState.KIND, name))
    n.serving_stats = dict(serving)
    store.update(NodeState.KIND, n.to_dict())


class TestEvacuation:
    """SLO-burn evacuation: the reconciler is live migration's third
    caller. A node whose serving heartbeat reports slo_burn >= limit
    gets its sessions drained OUT via the injected drainer — once per
    burn episode, with failures retried next tick and everything
    visible on kubeinfer_evacuations_total."""

    def _controller(self, store, clock, drainer, limit=1.0):
        return Controller(
            store, clock=clock, slo_burn_limit=limit, drainer=drainer,
        )

    def test_burning_node_drained_once_per_episode(self):
        store, clock, _ = setup(n_nodes=2)
        calls = []
        c = self._controller(store, clock, lambda n: calls.append(
            n.metadata.name) or True)
        before = evacuations_total.value("node-0", "drained")
        set_serving(store, "node-0", {"slo_burn": 2.5})
        res = c.reconcile_once()
        assert res.evacuations == 1
        assert calls == ["node-0"]
        # the node stays hot for the whole drain; re-reconciling must
        # not hammer /admin/drain (it would reset wait_drained clocks)
        for _ in range(3):
            assert c.reconcile_once().evacuations == 0
        assert calls == ["node-0"]
        assert evacuations_total.value("node-0", "drained") - before == 1

    def test_episode_clears_when_burn_subsides(self):
        store, clock, _ = setup(n_nodes=1)
        calls = []
        c = self._controller(store, clock, lambda n: calls.append(
            n.metadata.name) or True)
        set_serving(store, "node-0", {"slo_burn": 2.0})
        c.reconcile_once()
        # burn back under the limit: episode over, a fresh burn is a
        # fresh episode and gets a fresh drain request
        set_serving(store, "node-0", {"slo_burn": 0.1})
        c.reconcile_once()
        set_serving(store, "node-0", {"slo_burn": 3.0})
        c.reconcile_once()
        assert calls == ["node-0", "node-0"]

    def test_failed_drain_stays_candidate_and_is_counted(self):
        store, clock, _ = setup(n_nodes=1)
        attempts = []

        def flaky(n):
            attempts.append(n.metadata.name)
            if len(attempts) == 1:
                raise RuntimeError("serving plane unreachable")
            return True

        c = self._controller(store, clock, flaky)
        failed0 = evacuations_total.value("node-0", "failed")
        drained0 = evacuations_total.value("node-0", "drained")
        set_serving(store, "node-0", {"slo_burn": 2.0})
        res = c.reconcile_once()
        assert res.evacuations == 0  # the drainer raised
        res = c.reconcile_once()  # still burning: retried next tick
        assert res.evacuations == 1
        assert attempts == ["node-0", "node-0"]
        assert evacuations_total.value("node-0", "failed") - failed0 == 1
        assert evacuations_total.value("node-0", "drained") - drained0 == 1

    def test_already_draining_node_is_skipped(self):
        """An operator-initiated drain (heartbeat reports draining)
        must not be doubled by the reconciler, even above the limit."""
        store, clock, _ = setup(n_nodes=1)
        calls = []
        c = self._controller(store, clock, lambda n: calls.append(
            n.metadata.name) or True)
        set_serving(store, "node-0", {"slo_burn": 9.0, "draining": True})
        assert c.reconcile_once().evacuations == 0
        assert calls == []

    def test_disabled_without_limit_or_drainer(self):
        store, clock, _ = setup(n_nodes=1)
        set_serving(store, "node-0", {"slo_burn": 9.0})
        calls = []
        c = Controller(store, clock=clock, drainer=lambda n: calls.append(
            n.metadata.name) or True)  # limit defaults to 0 = off
        assert c.reconcile_once().evacuations == 0
        c2 = Controller(store, clock=clock, slo_burn_limit=1.0)  # no drainer
        assert c2.reconcile_once().evacuations == 0
        assert calls == []
