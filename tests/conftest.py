"""Test configuration: force an 8-device virtual CPU mesh BEFORE jax imports.

Multi-chip TPU hardware is not available in CI; all sharding tests run on
8 virtual CPU devices (the same code path pjit/shard_map take on a real TPU
mesh — only the device kind differs). Must run before any test module
imports jax. Explicit assignment (not setdefault): this machine exports
JAX_PLATFORMS=axon globally, and tests must not run on the experimental
single-chip tunnel backend.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize imports jax at interpreter startup, so jax's config
# already captured JAX_PLATFORMS=axon from the kernel env before this file
# ran — the env assignment above alone is inert. Update the live config too
# (backends are still uninitialized at collection time, so this takes
# effect; if it ever runs too late, the assertion below catches it).
import jax

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu", (
    f"tests must run on the virtual CPU mesh, got {jax.devices()}"
)
assert len(jax.devices()) == 8, jax.devices()

# Persistent XLA compilation cache: the suite is jit-compile-bound on this
# 1-core box (~15min cold, the top tests are 30-50s of pure compile), and
# the cache is keyed by HLO hash so reuse across runs is sound even as
# code changes (changed programs simply miss). Measured: a compile-heavy
# engine test drops 20s -> 8s on the second run. Keep the cache OUT of the
# repo tree (gitignore churn) but stable across runs.
_cache_dir = os.environ.get(
    "KUBEINFER_TEST_COMPILE_CACHE",
    os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "kubeinfer-test-jax-cache",
    ),
)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def scrubbed_pythonpath() -> str:
    """PYTHONPATH for spawned subprocesses: repo first, this box's axon
    sitecustomize removed (kubeinfer_tpu.utils.env owns the match rule;
    bench.py's CPU fallback uses the same one)."""
    from kubeinfer_tpu.utils.env import scrub_axon_pythonpath

    rest = scrub_axon_pythonpath()
    return REPO_ROOT + (os.pathsep + rest if rest else "")


# --- suite tiering (r4 verdict item 7) -------------------------------------
# Component markers are derived from the module name so they can never
# drift from the file layout; `slow` is opted into per-test where the
# compile cost lives (the suite is compile-bound, not run-bound, so
# slowness is a property of individual jit programs, not components).
# `make test-fast` runs `-m "not slow"`; CI's full tier runs everything.

_COMPONENT_BY_PREFIX = (
    (("test_solver", "test_problem", "test_backends", "test_sharded",
      "test_distributed", "test_multiprocess"),
     "solver"),
    (("test_inference", "test_flash", "test_sampling", "test_speculative"),
     "inference"),
    # resilience layer + fault-injection scenarios (`make test-chaos`);
    # pure controlplane work — runs under the same virtual CPU mesh
    (("test_chaos", "test_resilience"), "chaos"),
    # invariant linter + racecheck sentinel (kubeinfer_tpu/analysis/);
    # the sanitizer file covers the lockset detector + schedule fuzzer;
    # the protocol files cover the lifecycle spec (lint + replay oracle)
    (("test_static_analysis", "test_concurrency_sanitizer",
      "test_protocol"), "analysis"),
    # fleet router: scoring/summary round-trips + proxy; its chaos
    # scenario carries an explicit @pytest.mark.chaos on top
    (("test_router",), "router"),
    # tracing + serving latency breakdown (kubeinfer_tpu/observability/)
    (("test_observability",), "observability"),
)


def pytest_collection_modifyitems(config, items):
    import pytest

    for item in items:
        mod = item.module.__name__.rsplit(".", 1)[-1]
        for prefixes, marker in _COMPONENT_BY_PREFIX:
            if mod.startswith(prefixes):
                item.add_marker(getattr(pytest.mark, marker))
                break
        else:
            item.add_marker(pytest.mark.controlplane)


# --- concurrency sanitizer arming (ISSUE 9) ---------------------------------
# Every chaos-marked test (test_chaos, test_resilience, and router chaos
# scenarios) runs at KUBEINFER_RACECHECK=2: tracked locks feed the
# lock-order graph AND guard()-registered objects feed the Eraser
# lockset detector. Teardown fails the test on either oracle — a race
# the schedule happened not to lose is still a finding.
#
# ISSUE 17 adds a third oracle to the same fixture: a ProtocolMonitor
# streams every FlightRecorder.note through the request lifecycle spec
# (analysis/protocol.py) as it happens, so an illegal transition is a
# failure even when the bounded ring has already evicted the evidence.
# Legality-only at teardown: chains may legitimately end non-terminal
# (a test that stops mid-flight without sweeping, spec-group requests
# that never occupy a slot), so completeness is asserted only where a
# test knows its expected request set (protocol.assert_conformant).

import pytest  # noqa: E402 — after the jax mesh setup above


@pytest.fixture(autouse=True)
def _sanitizer_armed(request, monkeypatch):
    if request.node.get_closest_marker("chaos") is None:
        yield
        return
    monkeypatch.setenv("KUBEINFER_RACECHECK", "2")
    from kubeinfer_tpu.analysis import lockset, protocol, racecheck
    from kubeinfer_tpu.observability import flightrecorder

    racecheck.REGISTRY.reset()
    lockset.REGISTRY.reset()
    mon = protocol.ProtocolMonitor()
    prev = flightrecorder.get_monitor()
    flightrecorder.set_monitor(mon)
    try:
        yield
    finally:
        flightrecorder.set_monitor(prev)
    cycles = racecheck.REGISTRY.cycles()
    assert not cycles, f"lock-order cycles (deadlock potential): {cycles}"
    races = lockset.REGISTRY.races()
    assert not races, (
        "lockset data races:\n" + lockset.REGISTRY.render()
    )
    mon.assert_clean()
