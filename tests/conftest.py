"""Test configuration: force an 8-device virtual CPU mesh BEFORE jax imports.

Multi-chip TPU hardware is not available in CI; all sharding tests run on
8 virtual CPU devices (the same code path pjit/shard_map take on a real TPU
mesh — only the device kind differs). Must run before any test module
imports jax. Explicit assignment (not setdefault): this machine exports
JAX_PLATFORMS=axon globally, and tests must not run on the experimental
single-chip tunnel backend.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
