"""SchedulerBackend layer: native C++ scorer vs JAX solvers.

The native scorer is the serial baseline the TPU path is measured against
(BASELINE.json north star); these tests pin both tiers to the same
feasibility invariants so the benchmark comparison is apples-to-apples.
"""

import numpy as np
import pytest

from kubeinfer_tpu.api.types import SchedulerPolicy
from kubeinfer_tpu.scheduler import (
    JaxBackend,
    NativeGreedyBackend,
    SolveRequest,
    get_backend,
)

native = pytest.importorskip("kubeinfer_tpu.native")
if not native.native_available():
    pytest.skip("native library unavailable (no compiler?)", allow_module_level=True)


def small_request(**over):
    base = dict(
        job_gpu=np.array([2, 2, 4, 1], np.float32),
        job_mem_gib=np.array([10, 10, 20, 5], np.float32),
        node_gpu_free=np.array([4, 4, 8], np.float32),
        node_mem_free_gib=np.array([40, 40, 80], np.float32),
    )
    base.update(over)
    return SolveRequest(**base)


def check_capacity(req, assignment):
    used_gpu = np.zeros(req.num_nodes)
    used_mem = np.zeros(req.num_nodes)
    for j, n in enumerate(assignment):
        if n >= 0:
            used_gpu[n] += req.job_gpu[j]
            used_mem[n] += req.job_mem_gib[j]
    assert (used_gpu <= req.node_gpu_free + 1e-3).all()
    assert (used_mem <= req.node_mem_free_gib + 1e-3).all()


class TestNativeGreedy:
    def test_places_all_when_capacity_suffices(self):
        req = small_request()
        res = NativeGreedyBackend().solve(req)
        assert res.placed == 4
        assert (res.assignment >= 0).all()
        check_capacity(req, res.assignment)

    def test_respects_capacity_when_oversubscribed(self):
        req = small_request(
            job_gpu=np.full(10, 4.0, np.float32),
            job_mem_gib=np.full(10, 10.0, np.float32),
        )
        res = NativeGreedyBackend().solve(req)
        assert res.placed == 4  # 4+4+8 chips / 4 each
        check_capacity(req, res.assignment)

    def test_priority_wins_scarce_capacity(self):
        req = small_request(
            job_gpu=np.array([4, 4], np.float32),
            job_mem_gib=np.array([1, 1], np.float32),
            job_priority=np.array([0, 10], np.float32),
            node_gpu_free=np.array([4], np.float32),
            node_mem_free_gib=np.array([100], np.float32),
        )
        res = NativeGreedyBackend().solve(req)
        assert res.assignment[1] == 0
        assert res.assignment[0] == -1

    def test_cache_affinity_preferred(self):
        req = small_request(
            job_gpu=np.array([1], np.float32),
            job_mem_gib=np.array([1], np.float32),
            job_model=np.array([3], np.int32),
            node_gpu_free=np.array([8, 8], np.float32),
            node_mem_free_gib=np.array([64, 64], np.float32),
            node_cached=np.eye(8, dtype=np.uint8)[[0, 3]],  # node1 caches model 3
        )
        res = NativeGreedyBackend().solve(req)
        assert res.assignment[0] == 1

    def test_move_hysteresis_keeps_incumbent(self):
        req = small_request(
            job_gpu=np.array([1], np.float32),
            job_mem_gib=np.array([1], np.float32),
            job_current_node=np.array([1], np.int32),
            node_gpu_free=np.array([8, 8], np.float32),
            node_mem_free_gib=np.array([64, 64], np.float32),
        )
        res = NativeGreedyBackend().solve(req)
        assert res.assignment[0] == 1

    def test_gang_all_or_nothing(self):
        # gang of 3 with only 2 placeable slots -> whole gang unwound
        req = small_request(
            job_gpu=np.array([4, 4, 4, 1], np.float32),
            job_mem_gib=np.ones(4, np.float32),
            job_gang=np.array([7, 7, 7, -1], np.int32),
            node_gpu_free=np.array([4, 5], np.float32),
            node_mem_free_gib=np.full(2, 100, np.float32),
        )
        res = NativeGreedyBackend().solve(req)
        assert (res.assignment[:3] == -1).all()
        assert res.assignment[3] >= 0

    def test_empty_problem(self):
        req = small_request(
            job_gpu=np.zeros(0, np.float32),
            job_mem_gib=np.zeros(0, np.float32),
        )
        res = NativeGreedyBackend().solve(req)
        assert res.placed == 0
        assert res.assignment.shape == (0,)


class TestParityAcrossTiers:
    """Native and JAX tiers must agree on placement quality invariants."""

    @pytest.mark.parametrize(
        "policy",
        [SchedulerPolicy.NATIVE_GREEDY, SchedulerPolicy.JAX_GREEDY],
    )
    def test_full_placement_parity(self, policy):
        rng = np.random.default_rng(0)
        req = SolveRequest(
            job_gpu=rng.integers(1, 4, 64).astype(np.float32),
            job_mem_gib=rng.integers(1, 16, 64).astype(np.float32),
            node_gpu_free=np.full(32, 16.0, np.float32),
            node_mem_free_gib=np.full(32, 128.0, np.float32),
        )
        res = get_backend(policy).solve(req)
        assert res.placed == 64, f"{policy}: {res.placed}"
        check_capacity(req, res.assignment)

    @pytest.mark.parametrize(
        "policy",
        [SchedulerPolicy.NATIVE_GREEDY, SchedulerPolicy.JAX_GREEDY,
         SchedulerPolicy.JAX_AUCTION],
    )
    def test_oversubscribed_respects_capacity(self, policy):
        rng = np.random.default_rng(1)
        req = SolveRequest(
            job_gpu=rng.integers(1, 8, 128).astype(np.float32),
            job_mem_gib=rng.integers(1, 8, 128).astype(np.float32),
            node_gpu_free=np.full(8, 8.0, np.float32),
            node_mem_free_gib=np.full(8, 64.0, np.float32),
        )
        res = get_backend(policy).solve(req)
        assert 0 < res.placed < 128
        check_capacity(req, res.assignment)


class TestBackendRegistry:
    def test_get_backend_accepts_strings_and_caches(self):
        b1 = get_backend("native-greedy")
        b2 = get_backend(SchedulerPolicy.NATIVE_GREEDY)
        assert b1 is b2
        assert isinstance(get_backend("jax-auction"), JaxBackend)

    def test_unknown_policy_rejected(self):
        with pytest.raises(ValueError):
            get_backend("hungarian-on-abacus")


class TestAuctionGuard:
    """jax-auction is only sound for one-replica-per-node instances
    (core.solve_auction docstring); anything else must reroute to greedy
    rather than silently under-place (VERDICT r1 #6)."""

    def test_multi_replica_per_node_falls_back_and_places(self):
        from kubeinfer_tpu import metrics

        # 16 small jobs on 4 big nodes: pure auction would place at most
        # 4 (one per node); the guard reroutes to greedy and places all.
        req = SolveRequest(
            job_gpu=np.full(16, 1.0, np.float32),
            job_mem_gib=np.full(16, 4.0, np.float32),
            node_gpu_free=np.full(4, 8.0, np.float32),
            node_mem_free_gib=np.full(4, 64.0, np.float32),
        )
        before = metrics.auction_fallback_total.value()
        res = get_backend("jax-auction").solve(req)
        assert res.placed == 16
        assert res.policy == SchedulerPolicy.JAX_GREEDY.value
        assert res.extras.get("auction_fallback") == 1.0
        assert metrics.auction_fallback_total.value() == before + 1
        check_capacity(req, res.assignment)

    def test_whole_node_requests_stay_on_auction(self):
        from kubeinfer_tpu import metrics

        # 3 whole-node jobs on 4 nodes: the instance auction is built for.
        req = SolveRequest(
            job_gpu=np.full(3, 8.0, np.float32),
            job_mem_gib=np.full(3, 32.0, np.float32),
            node_gpu_free=np.full(4, 8.0, np.float32),
            node_mem_free_gib=np.full(4, 64.0, np.float32),
        )
        before = metrics.auction_fallback_total.value()
        res = get_backend("jax-auction").solve(req)
        assert res.placed == 3
        assert res.policy == SchedulerPolicy.JAX_AUCTION.value
        assert metrics.auction_fallback_total.value() == before
        # one replica per node, as auction guarantees
        placed_nodes = res.assignment[res.assignment >= 0]
        assert len(set(placed_nodes.tolist())) == len(placed_nodes)

    def test_more_jobs_than_nodes_falls_back(self):
        req = SolveRequest(
            job_gpu=np.full(8, 8.0, np.float32),
            job_mem_gib=np.full(8, 32.0, np.float32),
            node_gpu_free=np.full(4, 8.0, np.float32),
            node_mem_free_gib=np.full(4, 64.0, np.float32),
        )
        res = get_backend("jax-auction").solve(req)
        assert res.policy == SchedulerPolicy.JAX_GREEDY.value
        assert res.placed == 4  # capacity-bound, not auction-bound


class TestSeededBackendPath:
    def test_incumbents_survive_via_backend(self):
        """The backend layer decides the solver's static `seeded` flag
        from the request, and seeding must hold end to end (the
        production churn path — reconciler ticks re-solve with
        placements). The instance DISCRIMINATES: a higher-priority
        arrival is cache-steered onto the lower-priority incumbent's
        home node; unseeded, the arrival's window runs first, takes the
        node, and the incumbent is displaced — hysteresis alone cannot
        save it (verified: this assertion fails with seeded=False), so
        a regression in the seeded plumbing turns the test red."""
        cached = np.zeros((2, 4), bool)
        cached[0, 1] = True  # arrival's model (slot 1) cached on node 0
        req = SolveRequest(
            # job 0: high-priority arrival, whole node; job 1: low-
            # priority incumbent on node 0 (half the node). Model slot
            # 0 means "no affinity", so the arrival uses slot 1.
            job_gpu=np.array([8.0, 4.0], np.float32),
            job_mem_gib=np.array([8.0, 4.0], np.float32),
            job_priority=np.array([5.0, 0.0], np.float32),
            job_model=np.array([1, 0], np.int32),
            job_current_node=np.array([-1, 0], np.int32),
            node_gpu_free=np.array([8.0, 8.0], np.float32),
            node_mem_free_gib=np.array([64.0, 64.0], np.float32),
            node_cached=cached,
        )
        res = get_backend("jax-greedy").solve(req)
        assert res.placed == 2
        # seeded: the incumbent keeps its home; the arrival no longer
        # fits there (4 of 8 held) and lands on node 1 despite the
        # cache miss
        assert res.assignment[1] == 0
        assert res.assignment[0] == 1
