"""Engine observability layer: step profiler, flight recorder, SLO
monitor, and their serving surfaces.

Pins the PR 6 acceptance criteria: the profiler derives exact
goodput/occupancy/padding-waste numbers from explicit timestamps (no
wall-clock in the assertions), the flight recorder replays scheduler
decisions and auto-dumps on in-flight failure, burn rates follow the
SRE multi-window construction bit-for-bit, /metrics exposes the new
series, the debug endpoints are token-gated, Chrome traces carry the
counter tracks plus thread-name metadata, and NodeState heartbeats
advertise the engine's stats_summary() through a store round trip.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import jax
import pytest

from kubeinfer_tpu.observability import tracing
from kubeinfer_tpu.observability.flightrecorder import FlightRecorder
from kubeinfer_tpu.observability.slo import (
    DEFAULT_OBJECTIVES, SLOMonitor, SLOObjective,
)
from kubeinfer_tpu.observability.stepprof import StepProfiler
from kubeinfer_tpu.observability.tracing import SpanRecorder, Tracer


# --- step profiler ----------------------------------------------------------


class TestStepProfiler:
    def test_summary_is_exact_from_explicit_timestamps(self):
        prof = StepProfiler(n_slots=4, name="test.StepProf.l1")
        # one prefill (bucket 32, 24 live + 8 padded tokens) and two
        # decode steps at half occupancy (2/4 rows; 2 padded rows each)
        prof.record("prefill", bucket=32, live_rows=1, live_tokens=24,
                    padded_tokens=8, start=100.0, end=100.5)
        prof.record("decode", bucket=4, live_rows=2, live_tokens=2,
                    padded_tokens=2, start=100.5, end=100.6)
        prof.record("decode", bucket=4, live_rows=2, live_tokens=2,
                    padded_tokens=2, start=100.6, end=100.7)
        s = prof.summary(window_s=10.0, now=101.0)
        assert s["steps"] == 3
        assert s["goodput_tokens_per_sec"] == pytest.approx(28 / 10.0)
        # occupancy averages over DECODE dispatches only
        assert s["batch_occupancy"] == pytest.approx(0.5)
        assert s["padding_waste_frac"] == pytest.approx(12 / 40)
        assert s["compile_count"] == 2  # (prefill,32) and (decode,4)

    def test_window_excludes_old_records(self):
        prof = StepProfiler(n_slots=2, name="test.StepProf.l2")
        prof.record("decode", bucket=2, live_rows=2, live_tokens=2,
                    padded_tokens=0, start=10.0, end=10.1)
        prof.record("decode", bucket=2, live_rows=1, live_tokens=1,
                    padded_tokens=1, start=99.0, end=99.1)
        s = prof.summary(window_s=5.0, now=100.0)
        assert s["steps"] == 1
        assert s["goodput_tokens_per_sec"] == pytest.approx(1 / 5.0)
        assert s["batch_occupancy"] == pytest.approx(0.5)

    def test_compile_detected_once_per_shape(self):
        prof = StepProfiler(n_slots=2, name="test.StepProf.l3")
        a = prof.record("prefill", bucket=16, live_rows=1, live_tokens=8,
                        padded_tokens=8, start=0.0, end=1.0)
        b = prof.record("prefill", bucket=16, live_rows=1, live_tokens=8,
                        padded_tokens=8, start=1.0, end=1.1)
        c = prof.record("prefill", bucket=32, live_rows=1, live_tokens=8,
                        padded_tokens=24, start=1.1, end=2.0)
        assert (a.compiled, b.compiled, c.compiled) == (True, False, True)
        assert prof.compile_count == 2

    def test_snapshot_cursor_replays_each_record_once(self):
        prof = StepProfiler(n_slots=2, name="test.StepProf.l4")
        for i in range(5):
            prof.record("decode", bucket=2, live_rows=1, live_tokens=1,
                        padded_tokens=1, start=float(i), end=float(i) + 0.1)
        first = prof.snapshot(since_seq=-1)
        assert [r.seq for r in first] == [0, 1, 2, 3, 4]
        assert prof.snapshot(since_seq=first[-1].seq) == []
        prof.record("decode", bucket=2, live_rows=1, live_tokens=1,
                    padded_tokens=1, start=5.0, end=5.1)
        assert [r.seq for r in prof.snapshot(since_seq=4)] == [5]

    def test_ring_capacity_bounds_memory(self):
        prof = StepProfiler(n_slots=2, capacity=4, name="test.StepProf.l5")
        for i in range(10):
            prof.record("decode", bucket=2, live_rows=1, live_tokens=1,
                        padded_tokens=1, start=float(i), end=float(i) + 0.1)
        recs = prof.snapshot()
        assert [r.seq for r in recs] == [6, 7, 8, 9]

    def test_kv_sampled_every_n_and_carried_forward(self):
        calls = []

        def kv():
            calls.append(1)
            return (7, 3)

        prof = StepProfiler(n_slots=2, kv_sample_every=4, kv_stats=kv,
                            name="test.StepProf.l6")
        recs = [
            prof.record("decode", bucket=2, live_rows=1, live_tokens=1,
                        padded_tokens=1, start=float(i),
                        end=float(i) + 0.1)
            for i in range(6)
        ]
        assert len(calls) == 2  # seq 0 and seq 4
        # carried forward in between, never missing once sampled
        assert all(r.kv_in_use == 7 and r.kv_free == 3 for r in recs)

    def test_counter_events_shape(self):
        prof = StepProfiler(n_slots=2, name="test.StepProf.l7")
        prof.record("decode", bucket=2, live_rows=2, live_tokens=2,
                    padded_tokens=0, start=1.0, end=2.0)
        evs = prof.counter_events(pid=9)
        assert {e["name"] for e in evs} == {
            "batch_occupancy", "padded_tokens"
        }
        assert all(e["ph"] == "C" and e["pid"] == 9 for e in evs)
        occ = next(e for e in evs if e["name"] == "batch_occupancy")
        assert occ["ts"] == pytest.approx(2.0 * 1e6)
        assert occ["args"] == {"live_rows": 2}


# --- flight recorder --------------------------------------------------------


class TestFlightRecorder:
    def test_unknown_kind_rejected(self):
        fr = FlightRecorder(name="test.Flight.l1")
        with pytest.raises(ValueError):
            # lint: allow[protocol-kind] the unknown-kind rejection is the behavior under test
            fr.note("reboot")

    def test_ring_keeps_newest(self):
        fr = FlightRecorder(capacity=3, name="test.Flight.l2")
        for i in range(7):
            fr.note("submit", queue_depth=i, t=float(i), req=i,
                    prompt_tokens=1, max_new=1)
        assert len(fr) == 3
        assert [e.seq for e in fr.snapshot()] == [4, 5, 6]
        d = fr.to_dict()
        assert d["capacity"] == 3
        assert d["recorded"] == 7  # total ever noted, not just retained

    def test_render_replays_decisions_oldest_first(self):
        fr = FlightRecorder(name="test.Flight.l3")
        fr.note("backpressure", queue_depth=5, kv_in_use=30, kv_free=2,
                t=1.5, req=0, reason="pool", need_blocks=4)
        fr.note("evict", queue_depth=5, kv_in_use=28, kv_free=4, t=1.6,
                nodes=2)
        lines = fr.render().splitlines()
        assert len(lines) == 2
        assert "backpressure" in lines[0] and "need_blocks=4" in lines[0]
        assert "queue=5" in lines[0] and "kv=30/32" in lines[0]
        assert "evict" in lines[1]

    def test_snapshot_since_replays_exactly_once(self):
        fr = FlightRecorder(name="test.Flight.l5")
        for i in range(4):
            fr.note("submit", queue_depth=i, t=float(i), req=i,
                    prompt_tokens=1, max_new=1)
        first = fr.snapshot()
        cursor = first[-1].seq
        assert fr.snapshot(cursor) == []
        fr.note("evict", queue_depth=0, kv_in_use=1, kv_free=1, t=9.0,
                nodes=1)
        again = fr.snapshot(cursor)
        assert [e.seq for e in again] == [cursor + 1]
        # union of the two drains covers every event exactly once —
        # the StepProfiler cursor contract, now shared by both rings
        assert sorted(e.seq for e in first + again) == list(range(5))
        d = fr.to_dict(cursor)
        assert [e["seq"] for e in d["events"]] == [cursor + 1]
        assert d["recorded"] == 5

    def test_counter_events_skip_unsampled_kv(self):
        fr = FlightRecorder(name="test.Flight.l4")
        fr.note("submit", queue_depth=1, t=1.0, req=0,
                prompt_tokens=1, max_new=1)  # kv defaults to -1
        fr.note("admit", queue_depth=0, kv_in_use=8, kv_free=8, t=2.0,
                req=0, slot=0)
        evs = fr.counter_events(pid=3)
        depths = [e for e in evs if e["name"] == "queue_depth"]
        kv = [e for e in evs if e["name"] == "kv_blocks"]
        assert len(depths) == 2
        assert len(kv) == 1
        assert kv[0]["args"] == {"in_use": 8, "free": 8}


# --- SLO monitor ------------------------------------------------------------


class TestSLOMonitor:
    def test_burn_rate_is_exact(self):
        mon = SLOMonitor(
            objectives=(SLOObjective("ttft", 1.0, 0.9),),
            windows=(10.0, 100.0), name="test.SLO.l1",
        )
        # 4 requests in the short window, 1 bad: bad_frac 0.25 over a
        # 0.1 budget -> burn 2.5
        for t, v in ((95.0, 0.5), (96.0, 2.0), (97.0, 0.5), (98.0, 0.5)):
            mon.observe("ttft", v, t=t)
        rates = mon.burn_rates(now=100.0)["ttft"]
        assert rates[10.0] == pytest.approx(2.5)
        assert rates[100.0] == pytest.approx(2.5)
        rem = mon.budget_remaining(now=100.0)["ttft"]
        assert rem == pytest.approx(1.0 - 0.25 / 0.1)  # overrun: negative

    def test_short_window_separates_fresh_regression(self):
        mon = SLOMonitor(
            objectives=(SLOObjective("ttft", 1.0, 0.9),),
            windows=(10.0, 100.0), name="test.SLO.l2",
        )
        # old traffic all good; the last 10s all bad
        for t in range(10, 60, 10):
            mon.observe("ttft", 0.1, t=float(t))
        mon.observe("ttft", 5.0, t=95.0)
        rates = mon.burn_rates(now=100.0)["ttft"]
        assert rates[10.0] == pytest.approx(10.0)  # 1/1 bad over 0.1
        assert rates[100.0] == pytest.approx((1 / 6) / 0.1)

    def test_empty_window_burns_nothing(self):
        mon = SLOMonitor(name="test.SLO.l3")
        rates = mon.burn_rates(now=1000.0)
        assert all(
            r == 0.0 for per in rates.values() for r in per.values()
        )
        assert all(
            v == 1.0 for v in mon.budget_remaining(now=1000.0).values()
        )

    def test_unknown_objective_dropped(self):
        mon = SLOMonitor(
            objectives=(SLOObjective("ttft", 1.0, 0.9),),
            name="test.SLO.l4",
        )
        mon.observe("nope", 100.0, t=1.0)  # must not raise or count
        assert mon.burn_rates(now=2.0) == {"ttft": {
            w: 0.0 for w in mon.windows
        }}

    def test_parse_spec(self):
        obj = SLOObjective.parse("ttft:0.5:0.99")
        assert obj == SLOObjective("ttft", 0.5, 0.99)
        assert obj.budget == pytest.approx(0.01)
        for bad in ("ttft:0.5", "ttft:0.5:1.5", "ttft:0:0.9"):
            with pytest.raises(ValueError):
                SLOObjective.parse(bad)

    def test_snapshot_carries_counts_for_audit(self):
        mon = SLOMonitor(
            objectives=(SLOObjective("tpot", 0.1, 0.5),),
            windows=(60.0,), name="test.SLO.l5",
        )
        mon.observe("tpot", 0.2, t=10.0)
        mon.observe("tpot", 0.05, t=11.0)
        snap = mon.snapshot(now=20.0)
        w = snap["objectives"]["tpot"]["windows"]["60"]
        assert (w["bad"], w["total"]) == (1, 2)
        assert w["burn_rate"] == pytest.approx(1.0)  # 0.5/0.5
        assert snap["objectives"]["tpot"]["budget_remaining"] == \
            pytest.approx(0.0)

    def test_defaults_cover_the_breakdown_metrics(self):
        assert {o.name for o in DEFAULT_OBJECTIVES} == {
            "ttft", "tpot", "queue_wait"
        }


# --- span recorder under concurrent writers (satellite) ---------------------


class TestSpanRecorderConcurrency:
    def test_ring_overwrite_keeps_newest_without_torn_entries(self):
        capacity, n_threads, per_thread = 64, 8, 200
        rec = SpanRecorder(capacity=capacity, name="test.ConcRec.l1")

        def writer(t: int) -> None:
            tr = Tracer(f"w{t}", recorder=rec)
            for i in range(per_thread):
                tr.record_span(f"w{t}-{i}", start=float(i),
                               end=float(i) + 1.0, thread=t, index=i)

        threads = [
            threading.Thread(target=writer, args=(t,))
            for t in range(n_threads)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        spans = rec.snapshot()
        assert len(spans) == capacity
        by_thread: dict[int, list[int]] = {}
        for s in spans:
            # no torn entries: every surviving span is internally
            # consistent (name agrees with attrs, end stamped)
            t, i = s.attrs["thread"], s.attrs["index"]
            assert s.name == f"w{t}-{i}"
            assert s.end == pytest.approx(s.start + 1.0)
            by_thread.setdefault(t, []).append(i)
        # newest win: the ring holds the last `capacity` appends, so
        # each thread's survivors are a CONTIGUOUS tail slice of its
        # own append order — a surviving older span with a missing
        # newer one would mean the ring dropped from the wrong end
        for idxs in by_thread.values():
            assert idxs == list(range(idxs[0], idxs[0] + len(idxs)))


# --- engine integration -----------------------------------------------------


@pytest.fixture(scope="module")
def engine():
    from kubeinfer_tpu.inference import PRESETS, init_params
    from kubeinfer_tpu.inference.batching import ContinuousEngine

    cfg = PRESETS["tiny"]
    params = init_params(cfg, jax.random.PRNGKey(0))
    eng = ContinuousEngine(params, cfg, n_slots=2, cache_len=64).start()
    try:
        yield eng
    finally:
        eng.stop()


class TestEngineInstrumentation:
    def test_profiler_records_prefill_and_decode(self, engine):
        before = engine.profiler.snapshot()
        cursor = before[-1].seq if before else -1
        out = engine.generate([1, 2, 3, 4], max_new_tokens=3)
        assert len(out) == 3
        recs = engine.profiler.snapshot(since_seq=cursor)
        phases = [r.phase for r in recs]
        assert "prefill" in phases and "decode" in phases
        pre = next(r for r in recs if r.phase == "prefill")
        # suffix bucketing: 4-token prompt pads to the 4 bucket
        assert pre.bucket >= 4
        assert pre.live_tokens == 4
        assert pre.padded_tokens == pre.bucket - 4
        assert pre.dur_s >= 0.0
        for d in (r for r in recs if r.phase == "decode"):
            # this engine is n_slots=2: one live request decodes at
            # half occupancy; the record covers ONE fused window of
            # d.steps model steps (bucket == the compiled horizon K)
            assert d.n_slots == 2
            assert d.bucket == d.steps >= 1
            assert d.live_rows >= 1
            # every live row lands at least its first window token; a
            # mid-window EOS masks the tail into padding
            assert d.live_rows <= d.live_tokens <= d.live_rows * d.steps
            assert d.live_tokens + d.padded_tokens == 2 * d.steps

    def test_flight_recorder_sees_the_request_lifecycle(self, engine):
        n_before = len(engine.flight)
        engine.generate([5, 6, 7], max_new_tokens=2)
        new = [e for e in engine.flight.snapshot()][n_before:]
        kinds = [e.kind for e in new]
        assert "submit" in kinds and "admit" in kinds and "retire" in kinds
        admit = next(e for e in new if e.kind == "admit")
        assert admit.kv_in_use >= 0 and admit.kv_free >= 0
        assert "slot" in admit.detail and "suffix_bucket" in admit.detail
        retire = next(e for e in new if e.kind == "retire")
        assert retire.detail["tokens"] == 2

    def test_stats_summary_shape_and_sanity(self, engine):
        engine.generate([9, 8, 7], max_new_tokens=2)
        s = engine.stats_summary()
        assert set(s) == {
            "n_slots", "block_size", "queue_depth", "batch_occupancy",
            "goodput_tokens_per_sec", "padding_waste_frac",
            "kv_blocks_free", "kv_blocks_in_use", "prefix_hit_rate",
            "prefix_cached_tokens", "cache_summary",
            "tp_degree", "mesh_devices",
            "kv_dtype", "kv_pool_bytes",
            "weight_dtype", "model_param_bytes",
            "draining", "slo_burn",
        }
        # idle engine, no SLO monitor, no drain in flight: both
        # heartbeat signals sit at their resting values
        assert s["draining"] is False
        assert s["slo_burn"] == 0.0
        assert s["n_slots"] == 2
        # default engine runs the bf16 pool; pool bytes are static per
        # config and must be nonzero (the /metrics gauge leans on this)
        assert s["kv_dtype"] == "bf16"
        assert s["kv_pool_bytes"] > 0
        # weight-side twin of the pool pair: dtype string for fleet
        # rollout dashboards, static param bytes for the gauge
        assert s["weight_dtype"] == "bf16"
        assert s["model_param_bytes"] > 0
        # unsharded engine: the layout gauges report the degenerate
        # single-device layout, not an absent one
        assert s["tp_degree"] == 1
        assert s["mesh_devices"] == 1
        # the router's affinity signal: fingerprints must round-trip
        # JSON (63-bit masked) and stay within the advertised budget
        summ = s["cache_summary"]
        assert summ["block_size"] == s["block_size"]
        assert len(summ["fingerprints"]) <= 512
        assert all(0 <= fp < 2**63 for fp in summ["fingerprints"])
        assert s["prefix_cached_tokens"] >= 0
        assert s["queue_depth"] == 0  # nothing in flight now
        assert 0.0 <= s["batch_occupancy"] <= 1.0
        assert 0.0 <= s["padding_waste_frac"] <= 1.0
        assert 0.0 <= s["prefix_hit_rate"] <= 1.0
        assert s["goodput_tokens_per_sec"] > 0.0
        assert s["kv_blocks_free"] + s["kv_blocks_in_use"] > 0
        json.dumps(s)  # heartbeat embeds it verbatim: must serialize

    def test_fail_inflight_dumps_flight_recorder(self, caplog):
        from kubeinfer_tpu.inference import PRESETS, init_params
        from kubeinfer_tpu.inference.batching import ContinuousEngine

        cfg = PRESETS["tiny"]
        params = init_params(cfg, jax.random.PRNGKey(0))
        # never started: the scheduler thread must not race the
        # admit/fail below, making the in-flight state deterministic
        eng = ContinuousEngine(params, cfg, n_slots=2, cache_len=64)
        req = eng.submit([1, 2, 3], max_new_tokens=4)
        eng._admit_pending()  # places the request into slot 0
        assert any(r is req for r in eng._slot_req)
        with caplog.at_level(
            "WARNING", logger="kubeinfer_tpu.inference.batching"
        ):
            eng._fail_inflight()
        assert req.done.is_set() and req.failed
        kinds = [e.kind for e in eng.flight.snapshot()]
        assert kinds[-1] == "fail_inflight"
        assert "flight recorder dump" in caplog.text
        # the dump replays the lead-up decisions, not just the failure
        assert "submit" in caplog.text and "admit" in caplog.text
        # second sweep (stop() + epilogue both run it): nothing left in
        # flight, so no second dump
        n_events = len(eng.flight)
        eng._fail_inflight()
        assert len(eng.flight) == n_events


# --- serving surfaces: /metrics, debug endpoints, counter tracks ------------


@pytest.fixture(scope="module")
def serving(engine):
    from kubeinfer_tpu.inference.engine import Engine
    from kubeinfer_tpu.inference.server import InferenceServer

    srv = InferenceServer(
        Engine(engine.params, engine.cfg), model_id="obs-tiny", port=0,
        continuous=engine,
    ).start()
    try:
        yield srv
    finally:
        srv.stop()


def _post_completion(srv, body: dict):
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/v1/completions",
        data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


def _get(srv, path: str, token: str | None = None):
    headers = {"Authorization": f"Bearer {token}"} if token else {}
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}{path}", headers=headers
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, r.read()


class TestServingMetrics:
    def test_engine_series_on_metrics(self, serving):
        _post_completion(serving, {"prompt": [1, 2, 3], "max_tokens": 3})
        _, body = _get(serving, "/metrics")
        text = body.decode()
        for family, typ in (
            ("kubeinfer_engine_goodput_tokens_per_second", "gauge"),
            ("kubeinfer_engine_batch_occupancy", "gauge"),
            ("kubeinfer_engine_padding_waste_frac", "gauge"),
            ("kubeinfer_engine_queue_depth", "gauge"),
            ("kubeinfer_engine_step_duration_seconds", "histogram"),
            ("kubeinfer_engine_compiles_total", "counter"),
            ("kubeinfer_slo_burn_rate", "gauge"),
            ("kubeinfer_slo_budget_remaining", "gauge"),
        ):
            assert f"# TYPE {family} {typ}" in text
        m = serving.metrics
        assert m["step_duration"].count("prefill") >= 1
        assert m["step_duration"].count("decode") >= 1
        assert m["compiles"].value() >= 1
        assert m["occupancy"].value() > 0.0
        assert m["goodput"].value() > 0.0

    def test_step_records_fold_into_histogram_once(self, serving):
        _post_completion(serving, {"prompt": [4, 4], "max_tokens": 2})
        _get(serving, "/metrics")
        count = serving.metrics["step_duration"].count("decode")
        # a second scrape with no new steps must not re-observe
        _get(serving, "/metrics")
        assert serving.metrics["step_duration"].count("decode") == count

    def test_slo_gauges_follow_observations_exactly(self, serving):
        # default ttft objective: threshold 2.0s, objective 0.99. One
        # fabricated 100s observation in an otherwise-empty short
        # window would make burn = bad_frac / 0.01; feed via the same
        # monitor the breakdown path uses, then scrape
        mon = serving.slo
        t = tracing.now()
        mon.observe("ttft", 100.0, t=t)
        _get(serving, "/metrics")
        burn = serving.metrics["slo_burn"].value("ttft", "60s")
        counts = mon._window_counts("ttft", tracing.now())[60.0]
        assert burn == pytest.approx(
            (counts[0] / counts[1]) / 0.01
        )
        assert burn > 0.0
        assert serving.metrics["slo_budget"].value("ttft") < 1.0

    def test_breakdown_feeds_slo_monitor(self, serving):
        before = {
            name: len(ring) for name, ring in serving.slo._obs.items()
        }
        _post_completion(serving, {"prompt": [7, 7, 7], "max_tokens": 2})
        after = {
            name: len(ring) for name, ring in serving.slo._obs.items()
        }
        for name in ("ttft", "tpot", "queue_wait"):
            assert after[name] == before[name] + 1

    def test_debug_flightrecorder_endpoint(self, serving):
        _post_completion(serving, {"prompt": [2, 2], "max_tokens": 2})
        _, body = _get(serving, "/debug/flightrecorder")
        doc = json.loads(body)
        assert doc["capacity"] > 0
        kinds = {e["kind"] for e in doc["events"]}
        assert {"submit", "admit", "retire"} <= kinds
        for e in doc["events"]:
            assert {"seq", "t", "kind", "queue_depth", "kv_in_use",
                    "kv_free", "detail"} <= set(e)

    def test_debug_flightrecorder_since_cursor(self, serving):
        _post_completion(serving, {"prompt": [5, 5], "max_tokens": 2})
        _, body = _get(serving, "/debug/flightrecorder")
        cursor = json.loads(body)["events"][-1]["seq"]
        _, body = _get(serving, f"/debug/flightrecorder?since={cursor}")
        assert json.loads(body)["events"] == []
        _post_completion(serving, {"prompt": [5, 5, 5], "max_tokens": 2})
        _, body = _get(serving, f"/debug/flightrecorder?since={cursor}")
        fresh = json.loads(body)["events"]
        assert fresh and all(e["seq"] > cursor for e in fresh)

    def test_debug_flightrecorder_bad_since_is_400(self, serving):
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(serving, "/debug/flightrecorder?since=nope")
        assert ei.value.code == 400

    def test_sampled_out_requests_still_count_in_metrics(self, serving):
        # head sampling gates only the span RECORD path; the latency
        # observations come from the request timeline, so a sampled-out
        # request must still land in the SLO windows
        prev = tracing.set_span_sampling(1 << 30)
        try:
            spans_before = len(tracing.RECORDER.snapshot())
            ttft_before = len(serving.slo._obs["ttft"])
            _post_completion(serving, {"prompt": [6, 6], "max_tokens": 2})
            assert len(serving.slo._obs["ttft"]) == ttft_before + 1
            assert len(tracing.RECORDER.snapshot()) == spans_before
        finally:
            tracing.set_span_sampling(prev)

    def test_debug_slo_endpoint(self, serving):
        _, body = _get(serving, "/debug/slo")
        doc = json.loads(body)
        assert {"ttft", "tpot", "queue_wait"} <= set(doc["objectives"])
        ttft = doc["objectives"]["ttft"]
        assert set(ttft["windows"]) == {"60", "300", "1800"}
        for w in ttft["windows"].values():
            assert {"bad", "total", "burn_rate"} <= set(w)

    def test_debug_spans_carries_counter_tracks(self, serving):
        _post_completion(serving, {"prompt": [3, 3, 3], "max_tokens": 2})
        _, body = _get(serving, "/debug/spans")
        doc = json.loads(body)
        evs = doc["traceEvents"]
        counters = {e["name"] for e in evs if e["ph"] == "C"}
        assert {"batch_occupancy", "padded_tokens", "queue_depth",
                "kv_blocks"} <= counters
        procs = {
            e["args"]["name"] for e in evs
            if e.get("name") == "process_name"
        }
        assert "engine-counters" in procs
        # counter events live in their own process group, after the
        # span pids (so Perfetto renders them as separate tracks)
        counter_pid = next(
            e["pid"] for e in evs
            if e.get("name") == "process_name"
            and e["args"]["name"] == "engine-counters"
        )
        span_pids = {e["pid"] for e in evs if e["ph"] == "X"}
        assert counter_pid not in span_pids

    def test_thread_name_metadata_labels_trace_rows(self, serving):
        ctx = tracing.new_root_context()
        req = urllib.request.Request(
            f"http://127.0.0.1:{serving.port}/v1/completions",
            data=json.dumps(
                {"prompt": [6, 6], "max_tokens": 2}
            ).encode(),
            method="POST",
            headers={"Content-Type": "application/json",
                     "traceparent": ctx.traceparent()},
        )
        with urllib.request.urlopen(req, timeout=120):
            pass
        _, body = _get(serving, f"/debug/spans?trace_id={ctx.trace_id}")
        doc = json.loads(body)
        names = [
            e for e in doc["traceEvents"] if e.get("name") == "thread_name"
        ]
        assert names
        assert all(
            e["args"]["name"] == f"trace {ctx.trace_id[:8]}"
            for e in names
        )


class TestDebugAuth:
    @pytest.fixture()
    def armed(self, engine):
        from kubeinfer_tpu.inference.engine import Engine
        from kubeinfer_tpu.inference.server import InferenceServer

        srv = InferenceServer(
            Engine(engine.params, engine.cfg), model_id="authy", port=0,
            continuous=engine, token="sekrit",
        ).start()
        try:
            yield srv
        finally:
            srv.stop()

    @pytest.mark.parametrize("path", [
        "/debug/spans", "/debug/flightrecorder", "/debug/slo",
    ])
    def test_debug_requires_token_when_armed(self, armed, path):
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(armed, path)
        assert exc.value.code == 401
        assert json.loads(exc.value.read()) == {"error": "unauthorized"}
        status, body = _get(armed, path, token="sekrit")
        assert status == 200
        json.loads(body)

    def test_wrong_token_rejected(self, armed):
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(armed, "/debug/slo", token="wrong")
        assert exc.value.code == 401

    def test_health_and_metrics_stay_open(self, armed):
        status, body = _get(armed, "/health")
        assert status == 200 and body == b"OK"
        status, _ = _get(armed, "/metrics")
        assert status == 200


# --- heartbeat advertisement ------------------------------------------------


class TestHeartbeatServingStats:
    def test_heartbeat_round_trips_serving_stats(self, tmp_path):
        from kubeinfer_tpu.agent.node_agent import NodeAgent
        from kubeinfer_tpu.api.workload import NodeState
        from kubeinfer_tpu.controlplane.store import Store

        store = Store()
        stats = {"n_slots": 2, "queue_depth": 1,
                 "goodput_tokens_per_sec": 12.5, "batch_occupancy": 0.75}
        na = NodeAgent(
            store, "node-obs", gpu_capacity=4,
            gpu_memory_bytes=1 << 30, model_root=str(tmp_path),
            serving_stats=lambda: stats,
        )
        na._heartbeat()
        state = NodeState.from_dict(store.get(NodeState.KIND, "node-obs"))
        assert state.serving_stats == stats
        # second beat UPDATES the same object through the store
        stats2 = dict(stats, queue_depth=0)
        na._serving_stats = lambda: stats2
        na._heartbeat()
        state = NodeState.from_dict(store.get(NodeState.KIND, "node-obs"))
        assert state.serving_stats == stats2
        assert state.to_dict()["servingStats"] == stats2

    def test_failing_stats_callback_never_kills_the_heartbeat(
            self, tmp_path):
        from kubeinfer_tpu.agent.node_agent import NodeAgent
        from kubeinfer_tpu.api.workload import NodeState
        from kubeinfer_tpu.controlplane.store import Store

        store = Store()

        def boom():
            raise RuntimeError("stats backend down")

        na = NodeAgent(
            store, "node-boom", gpu_capacity=4,
            gpu_memory_bytes=1 << 30, model_root=str(tmp_path),
            serving_stats=boom,
        )
        na._heartbeat()  # must not raise
        state = NodeState.from_dict(store.get(NodeState.KIND, "node-boom"))
        assert state.serving_stats == {}
        assert state.heartbeat > 0.0

    def test_engine_summary_is_heartbeatable(self, engine, tmp_path):
        from kubeinfer_tpu.agent.node_agent import NodeAgent
        from kubeinfer_tpu.api.workload import NodeState
        from kubeinfer_tpu.controlplane.store import Store

        store = Store()
        engine.generate([1, 2], max_new_tokens=2)
        na = NodeAgent(
            store, "node-live", gpu_capacity=4,
            gpu_memory_bytes=1 << 30, model_root=str(tmp_path),
            serving_stats=engine.stats_summary,
        )
        na._heartbeat()
        state = NodeState.from_dict(store.get(NodeState.KIND, "node-live"))
        assert state.serving_stats["n_slots"] == 2
        assert state.serving_stats["goodput_tokens_per_sec"] >= 0.0
        assert 0.0 <= state.serving_stats["prefix_hit_rate"] <= 1.0
