"""Static legs of the lifecycle protocol verifier (ISSUE 17).

protolint fixtures inject one violation per rule and assert the
analyzer catches exactly it; known-good twins assert the conformant
idiom stays clean (zero false-positive budget, same contract as
test_static_analysis.py). donatecheck gets the same treatment for the
use-after-donation class. The whole-repo zero-findings gate lives in
test_static_analysis.py::test_repo_surface_has_zero_unsuppressed_findings
and picks these rules up automatically — the registry test here pins
that they are actually registered to be picked up.
"""

from __future__ import annotations

import textwrap

from kubeinfer_tpu.analysis import donatecheck, protocol
from kubeinfer_tpu.analysis.core import RULES, analyze_paths, analyze_source


def run_src(src: str, path: str = "pkg/sample.py", **kw):
    return analyze_source(textwrap.dedent(src), path, **kw)


def rules_of(findings):
    return [f.rule for f in findings]


def test_protocol_rules_registered():
    for rule in ("protocol-kind", "protocol-detail", "protocol-order",
                 "donate-use"):
        assert rule in RULES, rule


# --- protolint: kind + detail schema ---------------------------------------


def test_unknown_kind_flagged():
    fs = run_src(
        """
        def worker(fr):
            fr.note("reboot")
        """
    )
    assert rules_of(fs) == ["protocol-kind"]


def test_missing_required_detail_flagged():
    fs = run_src(
        """
        def worker(fr):
            fr.note("submit", req=1)
        """
    )
    assert rules_of(fs) == ["protocol-detail"]
    assert "prompt_tokens" in fs[0].message


def test_conformant_emit_clean():
    fs = run_src(
        """
        def worker(fr):
            fr.note("submit", req=1, prompt_tokens=8, max_new=4)
        """
    )
    assert fs == []


def test_kwargs_splat_defers_to_runtime():
    # a **splat hides the keys from the AST; the runtime monitor owns
    # the check there, so the static pass must not guess
    fs = run_src(
        """
        def worker(fr, kw):
            fr.note("submit", **kw)
        """
    )
    assert fs == []


def test_nonliteral_kind_flagged_outside_wrappers():
    fs = run_src(
        """
        def worker(fr, kind):
            fr.note(kind)
        """
    )
    assert rules_of(fs) == ["protocol-kind"]


def test_note_wrapper_exempt_from_nonliteral_kind():
    # the forwarding wrapper (ContinuousEngine._note) necessarily takes
    # kind as a variable; the emit SITES that call it are still checked
    fs = run_src(
        """
        class Engine:
            def _note(self, kind, **detail):
                return self.flight.note(kind, **detail)
        """
    )
    assert fs == []


def test_lint_binds_test_files_too():
    fs = run_src(
        """
        def test_thing(fr):
            fr.note("reboot")
        """,
        path="tests/test_sample.py",
    )
    assert rules_of(fs) == ["protocol-kind"]


# --- protolint: KINDS <-> SPEC drift ---------------------------------------


def test_kinds_tuple_matching_spec_clean():
    src = "KINDS = (" + ", ".join(repr(k) for k in protocol.SPEC) + ")\n"
    fs = analyze_source(src, "pkg/flightrecorder.py")
    assert fs == []


def test_kinds_tuple_drift_flagged_both_directions():
    fs = run_src('KINDS = ("submit", "bogus")\n',
                 path="pkg/flightrecorder.py")
    assert fs and all(f.rule == "protocol-kind" for f in fs)
    msgs = "\n".join(f.message for f in fs)
    # extra kind with no declared transitions, and spec kinds the
    # vocabulary dropped, both fail
    assert "bogus" in msgs
    assert "retire" in msgs


# --- protolint: per-method emit order --------------------------------------


def test_illegal_emit_order_flagged():
    fs = run_src(
        """
        def worker(fr):
            fr.note("retire", req=1, slot=0, tokens=4)
            fr.note("admit", req=1, slot=0)
        """
    )
    assert rules_of(fs) == ["protocol-order"]
    assert fs[0].line == 4  # lands on the SECOND emit of the pair


def test_legal_chain_order_clean():
    fs = run_src(
        """
        def worker(fr):
            fr.note("submit", req=1, prompt_tokens=8, max_new=4)
            fr.note("admit", req=1, slot=0)
            fr.note("retire", req=1, slot=0, tokens=4)
        """
    )
    assert fs == []


def test_branch_alternatives_do_not_pair():
    # retire and fail are both terminal, but they sit on EXCLUSIVE
    # branches — no execution emits both, so no pair
    fs = run_src(
        """
        def worker(fr, ok):
            if ok:
                fr.note("retire", req=1, slot=0, tokens=4)
            else:
                fr.note("fail", req=1, reason="boom")
        """
    )
    assert fs == []


def test_loop_back_edge_not_paired():
    # successive loop iterations serve DIFFERENT requests; pairing the
    # back-edge would flag every per-request loop in the scheduler
    fs = run_src(
        """
        def worker(fr, rids):
            for rid in rids:
                fr.note("retire", req=rid, slot=0, tokens=4)
        """
    )
    assert fs == []


def test_sibling_sweep_loops_pair_and_allow_suppresses():
    src = """
    def sweep(fr, live, queued):
        for rid in live:
            fr.note("fail", req=rid, reason="swept live")
        for rid in queued:
            fr.note("fail", req=rid, reason="swept queued")
    """
    fs = run_src(src)
    assert rules_of(fs) == ["protocol-order"]
    fixed = src.replace(
        '        for rid in queued:\n',
        '        for rid in queued:\n'
        '            # lint: allow[protocol-order] distinct request'
        ' populations\n',
    )
    assert run_src(fixed) == []


def test_engine_level_kinds_order_freely():
    fs = run_src(
        """
        def worker(fr):
            fr.note("retire", req=1, slot=0, tokens=4)
            fr.note("evict", nodes=3)
            fr.note("import", blocks=2)
        """
    )
    assert fs == []


# --- donatecheck ------------------------------------------------------------


def test_use_after_donation_flagged():
    fs = run_src(
        """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state):
            return state

        def caller(state):
            new = step(state)
            return state
        """
    )
    assert rules_of(fs) == ["donate-use"]
    assert "step" in fs[0].message


def test_same_statement_rebind_clean():
    # the repo idiom: `state = step(state)` — donation and rebind in
    # one statement never exposes the dead buffer
    fs = run_src(
        """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state):
            return state

        def caller(state):
            state = step(state)
            return state
        """
    )
    assert fs == []


def test_rebind_then_read_clean():
    fs = run_src(
        """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state):
            return state

        def caller(state):
            new = step(state)
            state = new
            return state
        """
    )
    assert fs == []


def test_metadata_reads_exempt():
    # shape/dtype live on the host-side aval, not the donated buffer
    fs = run_src(
        """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state):
            return state

        def caller(state):
            new = step(state)
            return new, state.shape, state.dtype
        """
    )
    assert fs == []


def test_attribute_donation_and_augassign_read():
    fs = run_src(
        """
        import jax

        step = jax.jit(lambda s: s, donate_argnums=(0,))

        def caller(self):
            out = step(self.buf)
            self.buf += 1
            return out
        """
    )
    assert rules_of(fs) == ["donate-use"]


def test_subattribute_bind_does_not_revive_parent():
    fs = run_src(
        """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state):
            return state

        def caller(self):
            new = step(self.state)
            self.state.meta = 1
            return self.state.cache
        """
    )
    # both the sub-attribute write-read and the trailing read are on
    # the dead parent
    assert fs and all(f.rule == "donate-use" for f in fs)


def test_branch_donation_merges_into_fallthrough():
    fs = run_src(
        """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state):
            return state

        def caller(state, hot):
            if hot:
                new = step(state)
            else:
                new = state
            return state
        """
    )
    assert rules_of(fs) == ["donate-use"]


def test_cross_file_registry_via_analyze_paths(tmp_path):
    # phase 1 collects donations repo-wide; a caller in ANOTHER file
    # still gets flagged
    (tmp_path / "kern.py").write_text(textwrap.dedent(
        """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def fused_step(state):
            return state
        """
    ))
    (tmp_path / "host.py").write_text(textwrap.dedent(
        """
        from kern import fused_step

        def caller(state):
            new = fused_step(state)
            return state
        """
    ))
    findings, nfiles = analyze_paths([tmp_path])
    assert nfiles == 2
    assert [f.rule for f in findings] == ["donate-use"]
    assert findings[0].path.endswith("host.py")


def test_collect_donations_sees_repo_jits():
    import ast
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    reg = {}
    for p in ("kubeinfer_tpu/inference/batching.py",
              "kubeinfer_tpu/inference/stepper.py"):
        reg.update(donatecheck.collect_donations(
            ast.parse((repo / p).read_text())
        ))
    # the decode/admit jits donate their state arg — if this set goes
    # empty the rule silently stops covering the paths it was built for
    assert "decode_window" in reg
    assert "_admit_slot" in reg
