"""Engine correctness: cached decode vs full forward, HF generate parity."""

from __future__ import annotations

import jax
import numpy as np
import pytest

from kubeinfer_tpu.inference import PRESETS, forward, init_params
from kubeinfer_tpu.inference.engine import Engine
from kubeinfer_tpu.inference.weights import params_from_state_dict

TINY = PRESETS["tiny"]


def ref_greedy(params, prompt: list[int], steps: int) -> list[int]:
    """Reference: greedy decode by full re-forward each step (no cache)."""
    import jax.numpy as jnp

    toks = list(prompt)
    for _ in range(steps):
        logits, _ = forward(
            params, jnp.asarray([toks], jnp.int32), TINY
        )
        toks.append(int(np.asarray(logits[0, -1]).argmax()))
    return toks[len(prompt):]


class TestEngine:
    @pytest.mark.slow
    def test_greedy_matches_uncached_reference(self):
        params = init_params(TINY, jax.random.PRNGKey(4))
        engine = Engine(params, TINY)
        prompt = [5, 17, 42, 7]
        out = engine.generate([prompt], max_new_tokens=6)
        assert out.tokens.shape == (1, 6)
        assert out.tokens[0].tolist() == ref_greedy(params, prompt, 6)

    def test_batch_with_ragged_prompts(self):
        params = init_params(TINY, jax.random.PRNGKey(4))
        engine = Engine(params, TINY)
        prompts = [[1, 2, 3], [9, 8, 7, 6, 5], [4]]
        out = engine.generate(prompts, max_new_tokens=4)
        for i, p in enumerate(prompts):
            assert out.tokens[i].tolist() == ref_greedy(params, p, 4), i

    def test_eos_stops_and_reports_length(self):
        params = init_params(TINY, jax.random.PRNGKey(4))
        engine = Engine(params, TINY)
        prompt = [5, 17, 42, 7]
        free = engine.generate([prompt], max_new_tokens=8)
        eos = int(free.tokens[0, 2])  # force EOS at the 3rd generated token
        out = engine.generate([prompt], max_new_tokens=8, eos_id=eos)
        assert out.lengths[0] == 3
        assert (out.tokens[0, 3:] == eos).all()  # post-EOS padded with EOS

    @pytest.mark.slow
    def test_chunked_prefill_multi_chunk_exact(self, monkeypatch):
        """Prefill split across several chunks must equal the one-shot
        forward (patch BOTH the chunk floor and the token budget small —
        prefill_chunk_for takes max(PREFILL_CHUNK, budget//B), so
        patching the floor alone would leave test-sized prompts
        single-chunk and silently stop covering the cross-chunk carry)."""
        import kubeinfer_tpu.inference.engine as eng

        monkeypatch.setattr(eng, "PREFILL_CHUNK", 8)
        monkeypatch.setattr(eng, "PREFILL_TOKEN_BUDGET", 8)
        params = init_params(TINY, jax.random.PRNGKey(4))
        engine = Engine(params, TINY)
        prompt = list(np.random.default_rng(13).integers(1, 200, 27))
        out = engine.generate([prompt], max_new_tokens=5)
        assert out.tokens[0].tolist() == ref_greedy(params, prompt, 5)

    def test_prefill_chunk_always_divides_bucket(self):
        """prefill_chunk_for must return a divisor of the bucket for ANY
        batch size: a non-dividing chunk makes the scan's final
        dynamic_slice clamp and silently re-process tokens at wrong
        positions (review-found with batch=3 -> 2048//3=682)."""
        from kubeinfer_tpu.inference.engine import (
            PROMPT_BUCKETS,
            prefill_chunk_for,
        )

        for bucket in PROMPT_BUCKETS:
            for batch in (1, 2, 3, 5, 7, 8, 16):
                c = prefill_chunk_for(batch, bucket)
                assert c >= 1 and bucket % c == 0, (batch, bucket, c)

    def test_three_row_group_prefill_exact(self):
        """End-to-end regression for the batch=3 divisor bug: 3 rows of
        the same >chunk length must decode exactly like the reference."""
        params = init_params(TINY, jax.random.PRNGKey(4))
        engine = Engine(params, TINY)
        rng = np.random.default_rng(5)
        prompts = [list(rng.integers(1, 200, 21)) for _ in range(3)]
        out = engine.generate(prompts, max_new_tokens=4)
        for b, p in enumerate(prompts):
            assert out.tokens[b].tolist() == ref_greedy(params, p, 4), b

    def test_single_new_token(self):
        # regression: max_new_tokens=1 used to feed lax.scan a 1-key xs
        # with length=0 and assert out
        params = init_params(TINY, jax.random.PRNGKey(4))
        out = Engine(params, TINY).generate([[5, 6, 7]], max_new_tokens=1)
        assert out.tokens.shape == (1, 1)
        assert out.tokens[0].tolist() == ref_greedy(params, [5, 6, 7], 1)

    @pytest.mark.slow
    def test_cache_narrower_than_prompt_bucket(self):
        # regression: max_cache_len=100 with a 70-token prompt bucketed to
        # 128 used to build a negative-width mask; capacity checks must be
        # against true lengths, the cache width against the bucket
        params = init_params(TINY, jax.random.PRNGKey(4))
        engine = Engine(params, TINY, max_cache_len=100)
        prompt = list(range(1, 71))
        out = engine.generate([prompt], max_new_tokens=8)
        assert out.tokens[0].tolist() == ref_greedy(params, prompt, 8)
        # and genuinely over-capacity requests still reject cleanly
        import pytest as _pytest

        with _pytest.raises(ValueError, match="context capacity"):
            engine.generate([prompt], max_new_tokens=40)

    def test_temperature_zero_equals_greedy_and_sampling_varies(self):
        params = init_params(TINY, jax.random.PRNGKey(4))
        engine = Engine(params, TINY)
        prompt = [3, 1, 4, 1, 5]
        g1 = engine.generate([prompt], max_new_tokens=5, temperature=0.0)
        g2 = engine.generate([prompt], max_new_tokens=5, temperature=0.0,
                             seed=99)
        assert g1.tokens.tolist() == g2.tokens.tolist()  # greedy is seedless
        s1 = engine.generate([prompt], max_new_tokens=16, temperature=5.0,
                             seed=1)
        s2 = engine.generate([prompt], max_new_tokens=16, temperature=5.0,
                             seed=2)
        assert s1.tokens.tolist() != s2.tokens.tolist()


class TestHFGenerateParity:
    @pytest.mark.slow
    def test_greedy_matches_transformers_generate(self):
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")
        hf_cfg = transformers.LlamaConfig(
            vocab_size=TINY.vocab_size,
            hidden_size=TINY.hidden_size,
            intermediate_size=TINY.intermediate_size,
            num_hidden_layers=TINY.num_hidden_layers,
            num_attention_heads=TINY.num_attention_heads,
            num_key_value_heads=TINY.num_key_value_heads,
            rms_norm_eps=TINY.rms_norm_eps,
            rope_theta=TINY.rope_theta,
            max_position_embeddings=TINY.max_position_embeddings,
            tie_word_embeddings=False,
            attention_bias=False,
            mlp_bias=False,
        )
        torch.manual_seed(3)
        model = transformers.LlamaForCausalLM(hf_cfg).eval()
        params = params_from_state_dict(
            model.state_dict(), TINY, dtype=np.float32
        )
        prompt = [11, 22, 33, 44, 55, 66]
        steps = 8
        with torch.no_grad():
            ref = model.generate(
                torch.tensor([prompt]), max_new_tokens=steps,
                do_sample=False, eos_token_id=None, pad_token_id=0,
            )[0, len(prompt):].tolist()
        out = Engine(params, TINY).generate([prompt], max_new_tokens=steps)
        assert out.tokens[0].tolist() == ref
