"""Pipeline- and expert-parallel parity vs dense references."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeinfer_tpu.inference import PRESETS, forward, init_params
from kubeinfer_tpu.inference.moe import (
    init_moe_params,
    make_ep_mesh,
    moe_block,
    moe_block_ep,
)
from kubeinfer_tpu.inference.pipeline import make_pp_mesh, pipeline_forward

TINY = PRESETS["tiny"]


class TestPipelineParallel:
    def test_pp_forward_matches_dense(self):
        params = init_params(TINY, jax.random.PRNGKey(0))
        rng = np.random.default_rng(1)
        tokens = jnp.asarray(
            rng.integers(0, TINY.vocab_size, (4, 12)), jnp.int32
        )
        ref, _ = forward(params, tokens, TINY)
        mesh = make_pp_mesh(pp=2)  # tiny has 2 layers -> 1 per stage
        out = pipeline_forward(params, tokens, TINY, mesh, n_microbatches=2)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
        )

    def test_pp_microbatch_count_independence(self):
        params = init_params(TINY, jax.random.PRNGKey(2))
        rng = np.random.default_rng(3)
        tokens = jnp.asarray(
            rng.integers(0, TINY.vocab_size, (4, 8)), jnp.int32
        )
        mesh = make_pp_mesh(pp=2)
        a = pipeline_forward(params, tokens, TINY, mesh, n_microbatches=2)
        b = pipeline_forward(params, tokens, TINY, mesh, n_microbatches=4)
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5
        )

    @pytest.mark.slow
    def test_pp_matches_dense_for_qwen2_and_mixtral(self):
        # pp must work for every family (specs derive from the layer
        # template, not a hardcoded llama key list — r2 review finding)
        from kubeinfer_tpu.inference import ModelConfig

        for kw in (
            {"qkv_bias": True},
            {"num_local_experts": 4, "num_experts_per_tok": 2},
        ):
            cfg = ModelConfig(
                vocab_size=128, hidden_size=32, intermediate_size=64,
                num_hidden_layers=4, num_attention_heads=4,
                num_key_value_heads=2, **kw,
            )
            params = init_params(cfg, jax.random.PRNGKey(4))
            toks = jnp.asarray(
                np.random.default_rng(6).integers(0, 128, (4, 8)),
                jnp.int32,
            )
            ref, _ = forward(params, toks, cfg)
            out = pipeline_forward(
                params, toks, cfg, make_pp_mesh(2), n_microbatches=2
            )
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
            )

    def test_pp_rejects_indivisible(self):
        params = init_params(TINY, jax.random.PRNGKey(0))
        tokens = jnp.zeros((3, 8), jnp.int32)
        mesh = make_pp_mesh(pp=2)
        with pytest.raises(ValueError, match="microbatches"):
            pipeline_forward(params, tokens, TINY, mesh, n_microbatches=2)


class TestExpertParallel:
    def test_ep_matches_dense(self):
        H, F, E = 32, 64, 8
        params = init_moe_params(jax.random.PRNGKey(4), H, F, E)
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.normal(size=(2, 6, H)), jnp.float32)
        ref = moe_block(params, x)
        mesh = make_ep_mesh(ep=4)  # 2 experts per device
        out = moe_block_ep(params, x, mesh)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    def test_router_uses_exactly_top_k(self):
        H, F, E = 16, 32, 8
        params = init_moe_params(jax.random.PRNGKey(6), H, F, E)
        from kubeinfer_tpu.inference.moe import _router_weights

        x = jnp.asarray(np.random.default_rng(7).normal(size=(1, 5, H)),
                        jnp.float32)
        w = np.asarray(_router_weights(params, x, top_k=2))
        nonzero = (w > 0).sum(axis=-1)
        assert (nonzero == 2).all()
        np.testing.assert_allclose(w.sum(-1), 1.0, rtol=1e-5)

    def test_ep_top1_routing(self):
        H, F, E = 16, 32, 4
        params = init_moe_params(jax.random.PRNGKey(8), H, F, E)
        x = jnp.asarray(np.random.default_rng(9).normal(size=(1, 4, H)),
                        jnp.float32)
        ref = moe_block(params, x, top_k=1)
        out = moe_block_ep(params, x, make_ep_mesh(ep=2), top_k=1)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
        )
