"""Speculative verify windows on the paged continuous batch: the
contracts that let the batcher run a draft model ahead of the target
without anyone being able to tell.

- **Token identity for ANY draft.** The acceptance rule
  (stepper.spec_accept) only ever emits the target's own samples — the
  draft gates how MANY land per window, never WHICH — so greedy and
  sampled streams must be bit-identical to the plain engine's for a
  self-draft (acceptance ~1.0) and an unrelated random draft
  (acceptance ~chance, every window rolling back) alike.

- **Rollback never leaks.** Boundary truncation coincides with
  retirement, parked rows drop their draft state and re-arm on warm
  readmit, and partially-accepted windows never reach the radix trie —
  so identity holds across warm admits and preemption cycles too.

- **Shape discipline.** One compiled verify shape per (spec_k, layout):
  every decode-phase advance routes through the fused verify dispatch
  (phase "verify", bucket == spec_k) and repeating a seen workload
  registers zero fresh first-seen shapes.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
import pytest

from kubeinfer_tpu.inference import PRESETS, init_params
from kubeinfer_tpu.inference.batching import (
    ContinuousEngine,
    PreemptionPolicy,
)
from kubeinfer_tpu.inference.sharding import EngineLayout

TINY = PRESETS["tiny"]
DRAFT_CFG = dataclasses.replace(TINY, num_hidden_layers=1)

AGGRESSIVE = PreemptionPolicy(
    threshold_s=0.0005, objective=0.5, burn_limit=0.5,
    cooldown_steps=1, min_progress=1,
)

SAMPLED = dict(temperature=0.8, seed=5, top_k=13)


@pytest.fixture(scope="module")
def params():
    return init_params(TINY, jax.random.PRNGKey(6))


@pytest.fixture(scope="module")
def draft():
    # unrelated 1-layer draft: same vocabulary, useless guesses —
    # the adversarial end of the acceptance spectrum
    return (init_params(DRAFT_CFG, jax.random.PRNGKey(7)), DRAFT_CFG)


def _engine(params, cfg=TINY, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("cache_len", 64)
    kw.setdefault("block_size", 8)
    return ContinuousEngine(params, cfg, **kw).start()


class TestVerifyIdentity:
    def test_cold_identity_random_draft(self, params, draft):
        """The fast tier-1 pin: an unrelated draft (near-zero
        acceptance, rollbacks every window) must still emit the plain
        engine's exact streams, greedy AND sampled."""
        rng = np.random.default_rng(41)
        prompt = rng.integers(0, TINY.vocab_size, 9).tolist()
        ref = _engine(params, max_window=1)
        try:
            want_g = ref.generate(prompt, max_new_tokens=9)
            want_s = ref.generate(prompt, max_new_tokens=9, **SAMPLED)
        finally:
            ref.stop()
        eng = _engine(params, spec_draft=draft, spec_k=4)
        try:
            got_g = eng.generate(prompt, max_new_tokens=9)
            got_s = eng.generate(prompt, max_new_tokens=9, **SAMPLED)
            stats = eng.scheduler_stats()
        finally:
            eng.stop()
        assert got_g == want_g
        assert got_s == want_s
        # the verify path actually ran, and the useless draft actually
        # rolled back — identity above wasn't a fallback to plain decode
        assert stats["spec_draft_tokens"] > 0
        assert stats["spec_rollbacks"] > 0
        assert (
            stats["spec_accepted_tokens"] <= stats["spec_draft_tokens"]
        )

    def test_self_draft_full_acceptance(self, params):
        """Draft == target: every greedy draft token matches the draw
        it guesses, so acceptance is total and no window rolls back —
        the throughput end of the spectrum, same identity."""
        rng = np.random.default_rng(42)
        prompt = rng.integers(0, TINY.vocab_size, 7).tolist()
        ref = _engine(params, max_window=1)
        try:
            want = ref.generate(prompt, max_new_tokens=8)
        finally:
            ref.stop()
        eng = _engine(params, spec_draft=(params, TINY), spec_k=4)
        try:
            got = eng.generate(prompt, max_new_tokens=8)
            stats = eng.scheduler_stats()
        finally:
            eng.stop()
        assert got == want
        assert stats["spec_draft_tokens"] > 0
        assert (
            stats["spec_accepted_tokens"] == stats["spec_draft_tokens"]
        )
        assert stats["spec_rollbacks"] == 0

    def test_bigram_draft_identity(self, params):
        """0-layer draft (embed/norm/lm_head only — the prompt-lookup /
        n-gram end of the draft spectrum, and what the bench pair
        uses): no draft KV exists, so the repair forward and propose
        scan run cache-free, and admit installs only ``prev``. Identity
        must hold like any other draft."""
        dcfg = dataclasses.replace(TINY, num_hidden_layers=0)
        dparams = {
            "embed_tokens": params["embed_tokens"],
            "layers": [],
            "norm": params["norm"],
            "lm_head": params["lm_head"],
        }
        rng = np.random.default_rng(47)
        prompt = rng.integers(0, TINY.vocab_size, 8).tolist()
        ref = _engine(params, max_window=1)
        try:
            want_g = ref.generate(prompt, max_new_tokens=8)
            want_s = ref.generate(prompt, max_new_tokens=8, **SAMPLED)
        finally:
            ref.stop()
        eng = _engine(params, spec_draft=(dparams, dcfg), spec_k=4)
        try:
            got_g = eng.generate(prompt, max_new_tokens=8)
            got_s = eng.generate(prompt, max_new_tokens=8, **SAMPLED)
            stats = eng.scheduler_stats()
        finally:
            eng.stop()
        assert got_g == want_g
        assert got_s == want_s
        assert stats["spec_draft_tokens"] > 0

    def test_warm_admit_identity(self, params, draft):
        """Radix reuse under speculation: the second admit of a prompt
        prefills from cached blocks, and the draft side re-prefills its
        dense cache over the FULL prompt — streams stay identical and
        the rollback rule (toks[:-1] at retire) kept partially-accepted
        tails out of the trie."""
        rng = np.random.default_rng(43)
        prompt = rng.integers(0, TINY.vocab_size, 9).tolist()
        ref = _engine(params, max_window=1)
        try:
            want_g = ref.generate(prompt, max_new_tokens=8)
            want_s = ref.generate(prompt, max_new_tokens=8, **SAMPLED)
        finally:
            ref.stop()
        eng = _engine(params, spec_draft=draft, spec_k=4)
        try:
            assert eng.generate(prompt, max_new_tokens=8) == want_g
            hits0 = eng.kv_cache_stats()["hits"]
            got_g = eng.generate(prompt, max_new_tokens=8)
            got_s = eng.generate(prompt, max_new_tokens=8, **SAMPLED)
            warm_hits = eng.kv_cache_stats()["hits"] - hits0
        finally:
            eng.stop()
        assert got_g == want_g
        assert got_s == want_s
        assert warm_hits >= 1, "second admit never reused the trie"

    @pytest.mark.slow
    def test_identity_across_preemption_cycles(self, params, draft):
        """Park/resume cycles against verify windows: parks drop the
        row's draft state and spec slack, readmits re-arm both — every
        request still emits the uncontended plain-engine stream."""
        rng = np.random.default_rng(44)
        prompts = [
            rng.integers(0, TINY.vocab_size, 5).tolist()
            for _ in range(12)
        ]
        kw = lambda i: dict(  # noqa: E731 - tiny per-index sampler knobs
            temperature=0.8 if i % 2 else 0.0,
            seed=70 + i, top_k=9 if i % 2 else 0,
        )
        ref = _engine(params, max_window=1)
        try:
            want = [ref.generate(p, max_new_tokens=8, **kw(i))
                    for i, p in enumerate(prompts)]
        finally:
            ref.stop()
        eng = _engine(params, spec_draft=draft, spec_k=4,
                      preemption=AGGRESSIVE)
        try:
            reqs = [eng.submit(p, max_new_tokens=8, **kw(i))
                    for i, p in enumerate(prompts)]
            for i, r in enumerate(reqs):
                assert r.done.wait(300), f"request {i} starved"
                assert not r.failed
            preempted = eng.preempted_total
            stats = eng.scheduler_stats()
        finally:
            eng.stop()
        assert preempted >= 1, "policy never parked anything"
        assert stats["spec_draft_tokens"] > 0
        for i, r in enumerate(reqs):
            assert r.out_tokens == want[i], f"request {i}"

    @pytest.mark.slow
    def test_tp2_identity(self, params, draft):
        """Sharded verify: the draft replicates onto the mesh and the
        fused verify partitions over tp — streams match the unsharded
        plain engine, and the verify shape set stays one bucket."""
        rng = np.random.default_rng(45)
        prompt = rng.integers(0, TINY.vocab_size, 7).tolist()
        ref = _engine(params, max_window=1)
        try:
            want_g = ref.generate(prompt, max_new_tokens=8)
            want_s = ref.generate(prompt, max_new_tokens=8, **SAMPLED)
        finally:
            ref.stop()
        eng = _engine(params, spec_draft=draft, spec_k=4,
                      layout=EngineLayout.build(2))
        try:
            got_g = eng.generate(prompt, max_new_tokens=8)
            got_s = eng.generate(prompt, max_new_tokens=8, **SAMPLED)
            stats = eng.scheduler_stats()
            buckets = {r.bucket for r in eng.profiler.snapshot()
                       if r.phase == "verify"}
        finally:
            eng.stop()
        assert got_g == want_g
        assert got_s == want_s
        assert stats["spec_draft_tokens"] > 0
        assert buckets == {4}


class TestVerifyShapes:
    def test_one_compiled_shape_per_k(self, params, draft):
        """Every decode-phase advance routes through the verify
        dispatch (no plain decode records at all), the bucket is
        spec_k, and a repeated workload registers zero fresh
        first-seen shapes."""
        rng = np.random.default_rng(46)
        prompt = rng.integers(0, TINY.vocab_size, 9).tolist()
        eng = _engine(params, spec_draft=draft, spec_k=4)
        try:
            eng.generate(prompt, max_new_tokens=9)
            recs = eng.profiler.snapshot()
            assert {r.bucket for r in recs if r.phase == "verify"} == {4}
            assert not [r for r in recs if r.phase == "decode"]
            c0 = eng.profiler.compile_count
            eng.generate(prompt, max_new_tokens=9)
            assert eng.profiler.compile_count == c0
        finally:
            eng.stop()
        eng2 = _engine(params, spec_draft=draft, spec_k=2)
        try:
            eng2.generate(prompt, max_new_tokens=9)
            buckets = {r.bucket for r in eng2.profiler.snapshot()
                       if r.phase == "verify"}
        finally:
            eng2.stop()
        assert buckets == {2}

    def test_constructor_validation(self, params, draft):
        dparams, dcfg = draft
        with pytest.raises(ValueError, match="spec_k must be >= 1"):
            ContinuousEngine(params, TINY, n_slots=2, cache_len=64,
                             block_size=8, spec_draft=draft, spec_k=0)
        bad_cfg = dataclasses.replace(dcfg, vocab_size=128)
        with pytest.raises(ValueError, match="vocabulary"):
            ContinuousEngine(params, TINY, n_slots=2, cache_len=64,
                             block_size=8,
                             spec_draft=(dparams, bad_cfg))
