"""Deterministic election/failover tests.

The reference has ZERO tests for its election logic (SURVEY.md §4) because
it reads time.Now() inline. With SimulatedClock every scenario — renewal,
expiry, takeover, split-brain steal races, clean handoff — is driven
step-by-step with no real sleeps.

Scenario parity: internal/agent/coordinator/election.go:47-225.
"""

import threading

from kubeinfer_tpu.controlplane import Store
from kubeinfer_tpu.coordination import (
    LEASE_DURATION_S,
    RETRY_INTERVAL_S,
    Lease,
    LeaseManager,
)
from kubeinfer_tpu.utils.clock import SimulatedClock


def mk(store, clock, ident, name="svc-cache-lease"):
    return LeaseManager(store, "default", name, ident, clock=clock)


class TestStateMachine:
    """Direct try_acquire_or_renew coverage (election.go:47-69)."""

    def test_first_caller_creates_and_holds(self):
        s, c = Store(), SimulatedClock()
        a = mk(s, c, "pod-a")
        assert a.try_acquire_or_renew() is True
        assert a.get_holder() == "pod-a"

    def test_second_caller_defers_to_live_holder(self):
        s, c = Store(), SimulatedClock()
        a, b = mk(s, c, "pod-a"), mk(s, c, "pod-b")
        assert a.try_acquire_or_renew()
        assert b.try_acquire_or_renew() is False

    def test_holder_renews_extends_lease(self):
        s, c = Store(), SimulatedClock()
        a, b = mk(s, c, "pod-a"), mk(s, c, "pod-b")
        assert a.try_acquire_or_renew()
        # keep renewing past several TTLs: b never steals
        for _ in range(5):
            c.advance(10.0)
            assert a.try_acquire_or_renew()
            assert b.try_acquire_or_renew() is False
        assert a.get_holder() == "pod-a"

    def test_expired_lease_is_stolen(self):
        s, c = Store(), SimulatedClock()
        a, b = mk(s, c, "pod-a"), mk(s, c, "pod-b")
        assert a.try_acquire_or_renew()
        c.advance(LEASE_DURATION_S + 0.1)  # a never renews: crashed
        assert b.try_acquire_or_renew() is True
        assert b.get_holder() == "pod-b"

    def test_stale_holder_renew_fails_after_steal(self):
        """A resurrected ex-coordinator must not clobber the new holder:
        its renew CAS targets a consumed resourceVersion."""
        s, c = Store(), SimulatedClock()
        a, b = mk(s, c, "pod-a"), mk(s, c, "pod-b")
        assert a.try_acquire_or_renew()
        stale = Lease.from_dict(s.get("Lease", "svc-cache-lease"))
        c.advance(LEASE_DURATION_S + 0.1)
        assert b.try_acquire_or_renew()
        # a wakes up with its stale view and tries to renew directly
        assert a._renew_lease(stale, c.now()) is False
        assert b.get_holder() == "pod-b"

    def test_steal_race_has_one_winner(self):
        """Split-brain guard: N stealers of one expired lease, one CAS wins
        (election.go:133-134 optimistic concurrency)."""
        s, c = Store(), SimulatedClock()
        holder = mk(s, c, "pod-dead")
        assert holder.try_acquire_or_renew()
        c.advance(LEASE_DURATION_S + 1)

        managers = [mk(s, c, f"pod-{i}") for i in range(8)]
        stale = Lease.from_dict(s.get("Lease", "svc-cache-lease"))
        results = []
        barrier = threading.Barrier(8)

        def attempt(m):
            barrier.wait()
            results.append(m._acquire_lease(
                Lease.from_dict(stale.to_dict()), c.now()))

        threads = [threading.Thread(target=attempt, args=(m,)) for m in managers]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sum(results) == 1
        assert s.get("Lease", "svc-cache-lease")["spec"]["holderIdentity"].startswith("pod-")

    def test_separate_lease_names_are_independent_elections(self):
        """One election per LLMService (lease name derives from cache group,
        cmd/agent/main.go:72)."""
        s, c = Store(), SimulatedClock()
        a = mk(s, c, "pod-a", name="svc1-cache-lease")
        b = mk(s, c, "pod-b", name="svc2-cache-lease")
        assert a.try_acquire_or_renew()
        assert b.try_acquire_or_renew()


class TestRunLoop:
    """Threaded loop + callbacks (election.go:170-225, agent role flips)."""

    def wait_until(self, clock, pred, max_sim_s=60.0, step=0.5):
        elapsed = 0.0
        while elapsed < max_sim_s:
            if pred():
                return True
            clock.advance_in_steps(step, step=step / 2)
            elapsed += step
        return pred()

    def test_election_failover_roles_flip(self):
        s, c = Store(), SimulatedClock()
        events: list[str] = []

        a = mk(s, c, "pod-a")
        b = mk(s, c, "pod-b")
        a.start(lambda: events.append("a+"), lambda: events.append("a-"))
        assert self.wait_until(c, a.is_coordinator)
        b.start(lambda: events.append("b+"), lambda: events.append("b-"))

        # b stays follower while a renews
        c.advance_in_steps(20.0)
        assert b.is_coordinator() is False

        # coordinator dies (stop without clean on_lost handoff: simulate by
        # killing the thread loop and never renewing again)
        a._stop.set()
        assert self.wait_until(c, b.is_coordinator, max_sim_s=LEASE_DURATION_S * 3)
        assert b.get_holder() == "pod-b"
        assert events[0] == "a+"
        assert "b+" in events
        b.stop()

    def test_failover_within_ttl_plus_retry(self):
        """Bound check: takeover happens within duration + one retry tick."""
        s, c = Store(), SimulatedClock()
        a, b = mk(s, c, "pod-a"), mk(s, c, "pod-b")
        assert a.try_acquire_or_renew()

        b.start(lambda: None, lambda: None)
        died_at = c.now()
        deadline = died_at + LEASE_DURATION_S + 2 * RETRY_INTERVAL_S

        took_over_at = None
        for _ in range(200):
            c.advance_in_steps(0.5, step=0.25)
            if b.is_coordinator():
                took_over_at = c.now()
                break
        b.stop()
        assert took_over_at is not None
        assert took_over_at <= deadline + 1.0

    def test_clean_stop_fires_on_lost(self):
        s, c = Store(), SimulatedClock()
        events: list[str] = []
        a = mk(s, c, "pod-a")
        a.start(lambda: events.append("+"), lambda: events.append("-"))
        assert self.wait_until(c, a.is_coordinator)
        a.stop()
        assert events == ["+", "-"]


class TestStressFuzz:
    """Randomized churn fuzz over the direct state machine: N participants
    with random crash/restart/renew interleavings must NEVER yield two
    simultaneous believers, and must converge to one live holder.

    The reference has no stress tier of any kind (SURVEY.md §5 "race
    detection: none"); this drives thousands of state transitions with a
    seeded RNG so failures replay deterministically.
    """

    def test_randomized_churn_single_believer_invariant(self):
        import numpy as np

        rng = np.random.default_rng(1234)
        s, c = Store(), SimulatedClock()
        N = 6
        peers = [mk(s, c, f"pod-{i}") for i in range(N)]
        # alive[i]: crashed participants stop calling try_acquire_or_renew
        # (exactly what a crashed process does); belief[i] mirrors the
        # return value of their last tick, i.e. what each peer believes.
        alive = [True] * N
        belief = [False] * N

        for step in range(3000):
            action = rng.random()
            if action < 0.05:
                victim = int(rng.integers(N))
                alive[victim] = False  # crash: stops ticking
                belief[victim] = False
            elif action < 0.10:
                revived = int(rng.integers(N))
                alive[revived] = True
            elif action < 0.35:
                c.advance(float(rng.uniform(0.5, 6.0)))
            else:
                i = int(rng.integers(N))
                if alive[i]:
                    belief[i] = peers[i].try_acquire_or_renew()
                    # INVARIANT: a true return means the store says so
                    if belief[i]:
                        assert peers[i].get_holder() == f"pod-{i}", step

            # INVARIANT: at most one participant believes it leads among
            # those whose belief is fresher than the lease TTL. Stronger
            # (and simpler): beliefs must agree with the single store
            # holder whenever the believer has ticked since the last
            # holder change — we check pairwise exclusivity of beliefs
            # refreshed in the same tick window by re-ticking all alive
            # peers at a frozen clock: exactly one may return True.
            if step % 200 == 199:
                confirmations = [
                    i for i in range(N)
                    if alive[i] and peers[i].try_acquire_or_renew()
                ]
                assert len(confirmations) <= 1, (step, confirmations)

        # convergence: revive everyone, advance past TTL, tick twice:
        # exactly one believer remains
        alive = [True] * N
        c.advance(LEASE_DURATION_S + 1)
        results = [p.try_acquire_or_renew() for p in peers]
        results = [p.try_acquire_or_renew() for p in peers]
        assert sum(results) == 1, results
