"""Train-step tests: loss decreases; sharded step matches single-device."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from kubeinfer_tpu.inference import PRESETS, init_params
from kubeinfer_tpu.inference.sharding import make_inference_mesh, shard_params
from kubeinfer_tpu.inference.train import (
    causal_lm_loss,
    sharded_train_step,
    train_step,
)

TINY = PRESETS["tiny"]


def batch(B=4, T=16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, TINY.vocab_size, (B, T)), jnp.int32)


class TestTrainStep:
    def test_loss_decreases_on_overfit_batch(self):
        params = init_params(TINY, jax.random.PRNGKey(0))
        toks = batch()
        first = float(causal_lm_loss(params, toks, TINY))
        loss = None
        for _ in range(8):
            params, loss = train_step(params, toks, TINY, lr=5e-2)
        assert float(loss) < first * 0.9

    def test_sharded_step_matches_single_device(self):
        toks = batch(seed=2)
        p_single = init_params(TINY, jax.random.PRNGKey(1))
        _, ref_loss = train_step(p_single, toks, TINY)

        mesh = make_inference_mesh(tp=2, sp=1, dp=4)
        p_sharded = shard_params(
            init_params(TINY, jax.random.PRNGKey(1)), mesh, TINY
        )
        step = sharded_train_step(mesh, TINY)
        _, loss = step(p_sharded, jax.device_put(
            toks, jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec("dp", None)
            ),
        ))
        np.testing.assert_allclose(
            float(loss), float(ref_loss), rtol=2e-5, atol=2e-5
        )

    def test_multi_step_keeps_sharding_and_converges(self):
        mesh = make_inference_mesh(tp=2, sp=1, dp=4)
        params = shard_params(
            init_params(TINY, jax.random.PRNGKey(3)), mesh, TINY
        )
        toks = jax.device_put(batch(seed=5), jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("dp", None)
        ))
        step = sharded_train_step(mesh, TINY)
        losses = []
        for _ in range(6):
            params, loss = step(params, toks)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
