"""Train-step tests: loss decreases; sharded step matches single-device."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

import pytest

from kubeinfer_tpu.inference import PRESETS, init_params
from kubeinfer_tpu.inference.sharding import make_inference_mesh, shard_params
from kubeinfer_tpu.inference.train import (
    causal_lm_loss,
    sharded_train_step,
    train_step,
)

TINY = PRESETS["tiny"]


def batch(B=4, T=16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, TINY.vocab_size, (B, T)), jnp.int32)


class TestTrainStep:
    def test_loss_decreases_on_overfit_batch(self):
        params = init_params(TINY, jax.random.PRNGKey(0))
        toks = batch()
        first = float(causal_lm_loss(params, toks, TINY))
        loss = None
        for _ in range(8):
            params, loss = train_step(params, toks, TINY, lr=5e-2)
        assert float(loss) < first * 0.9

    def test_sharded_step_matches_single_device(self):
        toks = batch(seed=2)
        p_single = init_params(TINY, jax.random.PRNGKey(1))
        _, ref_loss = train_step(p_single, toks, TINY)

        mesh = make_inference_mesh(tp=2, sp=1, dp=4)
        p_sharded = shard_params(
            init_params(TINY, jax.random.PRNGKey(1)), mesh, TINY
        )
        step = sharded_train_step(mesh, TINY)
        _, loss = step(p_sharded, jax.device_put(
            toks, jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec("dp", None)
            ),
        ))
        np.testing.assert_allclose(
            float(loss), float(ref_loss), rtol=2e-5, atol=2e-5
        )

    def test_multi_step_keeps_sharding_and_converges(self):
        mesh = make_inference_mesh(tp=2, sp=1, dp=4)
        params = shard_params(
            init_params(TINY, jax.random.PRNGKey(3)), mesh, TINY
        )
        toks = jax.device_put(batch(seed=5), jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("dp", None)
        ))
        step = sharded_train_step(mesh, TINY)
        losses = []
        for _ in range(6):
            params, loss = step(params, toks)
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestSequenceParallelTraining:
    """Long-context distributed training: the ring-attention forward
    differentiates (ppermute transposes under AD), so the sp mesh axis
    shards the sequence for TRAINING, not just serving."""

    @pytest.mark.slow
    def test_sp_grads_match_dense(self):
        from kubeinfer_tpu.inference.sharding import make_inference_mesh
        from kubeinfer_tpu.inference.train import (
            causal_lm_loss,
            sp_causal_lm_loss,
        )

        cfg = PRESETS["tiny"]
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(
            rng.integers(1, cfg.vocab_size, (2, 33)), jnp.int32
        )
        mesh = make_inference_mesh(tp=1, sp=2)
        l_sp, g_sp = jax.value_and_grad(sp_causal_lm_loss)(
            params, tokens, cfg, mesh
        )
        l_d, g_d = jax.value_and_grad(causal_lm_loss)(params, tokens, cfg)
        np.testing.assert_allclose(float(l_sp), float(l_d), rtol=1e-5)
        for a, b in zip(jax.tree.leaves(g_sp), jax.tree.leaves(g_d)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-6, rtol=1e-4
            )

    def test_sp_step_decreases_loss(self):
        from kubeinfer_tpu.inference.sharding import make_inference_mesh
        from kubeinfer_tpu.inference.train import sp_train_step

        cfg = PRESETS["tiny"]
        params = init_params(cfg, jax.random.PRNGKey(1))
        rng = np.random.default_rng(1)
        tokens = jnp.asarray(
            rng.integers(1, cfg.vocab_size, (2, 17)), jnp.int32
        )
        mesh = make_inference_mesh(tp=1, sp=2)
        step = sp_train_step(mesh, cfg, lr=1e-2)
        params, l0 = step(params, tokens)
        for _ in range(4):
            params, loss = step(params, tokens)
        assert float(loss) < float(l0)
