"""Quantized int8 weights: fused dequant-matmul kernel/twin
bit-identity, per-tile symmetric absmax round-trip bounds, and
end-to-end token parity of the int8 engine against bf16 — cold, warm
(radix readmit), chunked prefill, speculative verify, and tp=2.

The kernel runs in interpreter mode (CPU test mesh); the twin is the
contract — quant_matmul must match quant_matmul_jnp BIT-for-bit per
the repo's kernel/twin invariant. Engine parity uses the exact-grid
construction from the TP tests: the reference engine holds the
DEQUANTIZED f32 weights (so both engines see the same quantization
grid and the remaining difference is f32 ulp noise, orders below
random-init logit gaps), which makes greedy AND sampled streams
token-identical rather than tolerance-matched.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from kubeinfer_tpu.inference import PRESETS, init_params
from kubeinfer_tpu.inference.batching import (
    ContinuousEngine,
    EngineOverloadedError,
)
from kubeinfer_tpu.inference.sharding import EngineLayout
from kubeinfer_tpu.inference.weight_quant import (
    QUANT_LEAVES,
    dequantize_params,
    dequantize_weight,
    params_weight_dtype,
    quant_matmul,
    quant_matmul_dense,
    quant_matmul_jnp,
    quantize_params,
    quantize_weight,
)

TINY = PRESETS["tiny"]


class TestQuantMatmulKernelTwin:
    def _check(self, M, K, N, bm, bn, bk, dtype, tile=128, seed=31):
        kx, kw = jax.random.split(jax.random.PRNGKey(seed))
        x = jax.random.normal(kx, (M, K), jnp.float32).astype(dtype)
        w = jax.random.normal(kw, (K, N), jnp.float32)
        d = quantize_weight(w, tile=tile)
        got = quant_matmul(
            x, d["qw"], d["scale"],
            block_m=bm, block_n=bn, block_k=bk, interpret=True,
        )
        twin = quant_matmul_jnp(
            x, d["qw"], d["scale"], block_m=bm, block_n=bn, block_k=bk,
        )
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(twin),
            err_msg="quant_matmul kernel/twin bit-identity",
        )
        # semantic cross-check against the engine's own GSPMD/CPU
        # fallback (whole-array dot): tolerance-class, because the
        # tiled accumulation order legitimately differs
        want = quant_matmul_dense(x, d["qw"], d["scale"])
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            atol=3e-2, rtol=1e-1,
        )
        assert np.all(np.isfinite(np.asarray(got, np.float32)))

    def test_ragged_everything_f32(self):
        # M, K, N all off-grid: every pad path (m tail, k zero-fill,
        # n tail crossing a scale tile) is live in one shape
        self._check(7, 64, 200, 8, 128, 32, jnp.float32, tile=64)

    def test_aligned_bf16(self):
        # the clean serving shape: bf16 activations, everything on the
        # 128 grid, one tile per block_n
        self._check(16, 128, 128, 8, 128, 128, jnp.bfloat16)

    def test_prime_dims_multirow_grid(self):
        # prime-ish dims with a multi-row m grid and deep k loop: the
        # scratch accumulator must carry across 7 k-steps per (m, n)
        self._check(130, 100, 257, 16, 128, 16, jnp.float32)

    def test_single_row_small_tile(self):
        # decode shape (M=1) with tile smaller than block_n: one
        # kernel n-block spans two scale tiles
        self._check(1, 64, 64, 8, 64, 32, jnp.bfloat16, tile=32)


class TestQuantRoundTrip:
    def test_roundtrip_error_bound(self):
        # symmetric absmax: |w - deq(q(w))| <= scale/2 per element,
        # scale = amax/127 per (out-tile) — the PINNED bound the
        # engine-parity and bench accuracy gates lean on
        w = jax.random.normal(
            jax.random.PRNGKey(3), (96, 200), jnp.float32
        )
        d = quantize_weight(w, tile=64)
        deq = dequantize_weight(d, dtype=jnp.float32)
        err = jnp.abs(deq - w)
        bound = d["scale"][None, :] / 2.0 * (1.0 + 1e-5)
        assert bool(jnp.all(err <= bound)), float(jnp.max(err / bound))
        # scale really is per-column-constant-per-tile amax/127
        amax = jnp.max(jnp.abs(w[:, :64]), axis=None)
        np.testing.assert_allclose(
            float(d["scale"][0]), float(amax) / 127.0, rtol=1e-6
        )

    def test_zero_tile_scale_one(self):
        # all-zero tiles must quantize losslessly with scale 1.0 (not
        # 0, which would NaN nothing here but corrupt requant; not
        # amax=0/127)
        w = jnp.zeros((32, 64), jnp.float32)
        d = quantize_weight(w, tile=32)
        assert bool(jnp.all(d["qw"] == 0))
        np.testing.assert_array_equal(np.asarray(d["scale"]), 1.0)
        assert bool(jnp.all(dequantize_weight(d) == 0))

    def test_requant_exact(self):
        # dequant -> requant is EXACT: the amax element quantizes to
        # +-127, so the recovered scale round-trips — the invariant
        # that makes checkpoint restore + engine re-ingest lossless
        w = jax.random.normal(jax.random.PRNGKey(9), (48, 96))
        d1 = quantize_weight(w, tile=32)
        d2 = quantize_weight(dequantize_weight(d1, jnp.float32), tile=32)
        np.testing.assert_array_equal(np.asarray(d1["qw"]),
                                      np.asarray(d2["qw"]))
        np.testing.assert_array_equal(np.asarray(d1["scale"]),
                                      np.asarray(d2["scale"]))

    def test_double_quantize_guard(self):
        params = init_params(TINY, jax.random.PRNGKey(0),
                             weight_dtype="int8")
        assert params_weight_dtype(params) == "int8"
        with pytest.raises(ValueError, match="already weight-quantized"):
            quantize_params(params)
        # the engine-side guard: int8-held params + bf16 request is a
        # config error, never a silent dequant
        with pytest.raises(ValueError, match="weight-quantized"):
            ContinuousEngine(params, TINY, n_slots=2, cache_len=64,
                             block_size=8, weight_dtype="bf16")

    def test_quantized_tree_structure(self):
        params = init_params(TINY, jax.random.PRNGKey(0),
                             dtype=jnp.bfloat16, weight_dtype="int8")
        layer = params["layers"][0]
        for name in QUANT_LEAVES:
            leaf = layer[name]
            assert set(leaf) == {"qw", "scale"}
            assert leaf["qw"].dtype == jnp.int8
            assert leaf["scale"].dtype == jnp.float32
            assert leaf["scale"].shape == (leaf["qw"].shape[1],)
        # precision-critical leaves stay bf16
        assert params["embed_tokens"].dtype == jnp.bfloat16
        assert params["norm"].dtype == jnp.bfloat16

    def test_bf16_mode_is_untouched(self):
        # weight_dtype="bf16" must be byte-identical to the pre-quant
        # world: no dict leaves anywhere, and the degenerate layout
        # passes params through by identity (same compile cache)
        params = init_params(TINY, jax.random.PRNGKey(0))
        assert params_weight_dtype(params) == "bf16"
        assert all(
            not isinstance(v, dict)
            for layer in params["layers"] for v in layer.values()
        )
        eng = ContinuousEngine(params, TINY, n_slots=2, cache_len=64,
                               block_size=8)
        assert eng.weight_dtype == "bf16"
        assert eng.params is params


class TestEngineTokenParity:
    """int8 engine vs the SAME-grid f32 reference, token for token.

    The reference holds dequantize_params(quantize_params(w)) — both
    engines see identical quantized values, so the only divergence is
    dense-vs-scaled matmul ulp noise (~1e-7) against random-init logit
    gaps (~1e-2): greedy and sampled streams must match exactly, the
    same dominance argument EngineLayout's TP parity rests on.
    """

    def _engines(self, model="tiny", tp=1, **kw):
        cfg = PRESETS[model]
        params = init_params(cfg, jax.random.PRNGKey(6))
        qp = quantize_params(params)
        mk = dict(n_slots=2, cache_len=128, block_size=16,
                  prefill_chunk_blocks=0)
        mk.update(kw)
        if tp > 1:
            mk["layout"] = EngineLayout.build(tp)
        ref = ContinuousEngine(dequantize_params(qp, jnp.float32), cfg,
                               **mk)
        if tp > 1:
            mk["layout"] = EngineLayout.build(tp)
        got = ContinuousEngine(qp, cfg, weight_dtype="int8", **mk)
        assert got.weight_dtype == "int8"
        assert got.model_param_bytes < ref.model_param_bytes
        return cfg, ref, got

    def _run(self, eng, prompts, max_new, **samp):
        eng.start()
        try:
            reqs = [eng.submit(p, max_new_tokens=max_new, **samp)
                    for p in prompts]
            for r in reqs:
                assert r.done.wait(timeout=120)
                assert not r.failed, r.failed
            return [list(r.out_tokens) for r in reqs]
        finally:
            eng.stop()

    def test_greedy_and_sampled_identity(self):
        cfg, ref, got = self._engines()
        rng = np.random.default_rng(11)
        prompts = [
            rng.integers(0, cfg.vocab_size, 5).tolist(),
            rng.integers(0, cfg.vocab_size, 37).tolist(),
        ]
        assert self._run(ref, prompts, 40) == self._run(got, prompts, 40)
        # fresh pair for the sampled streams: engines are one-shot
        # (stop() is terminal), and seeded sampling must match anyway
        cfg, ref, got = self._engines()
        samp = dict(temperature=0.8, seed=5, top_k=13)
        assert (self._run(ref, prompts, 24, **samp)
                == self._run(got, prompts, 24, **samp))

    def test_greedy_identity_warm_admit(self):
        # radix warm path: the second submit re-admits from cached KV
        # blocks computed BY the quantized forward — prefix reuse must
        # reproduce the cold path's tokens exactly on both engines
        cfg, ref, got = self._engines()
        rng = np.random.default_rng(12)
        prompt = rng.integers(0, cfg.vocab_size, 33).tolist()
        for eng in (ref, got):
            eng.start()
        try:
            outs = {}
            for name, eng in (("ref", ref), ("got", got)):
                r1 = eng.submit(prompt, max_new_tokens=24)
                assert r1.done.wait(timeout=120)
                r2 = eng.submit(prompt, max_new_tokens=24)
                assert r2.done.wait(timeout=120)
                assert list(r1.out_tokens) == list(r2.out_tokens)
                outs[name] = list(r1.out_tokens)
            assert outs["ref"] == outs["got"]
        finally:
            ref.stop()
            got.stop()

    def test_greedy_identity_chunked_prefill(self):
        cfg, ref, got = self._engines(prefill_chunk_blocks=2)
        rng = np.random.default_rng(13)
        prompts = [rng.integers(0, cfg.vocab_size, 89).tolist()]
        assert self._run(ref, prompts, 20) == self._run(got, prompts, 20)

    def test_greedy_identity_spec_verify(self):
        # speculative path: the int8 TARGET verifies draft proposals —
        # verify_window runs the quantized forward. The draft stays
        # plain (self-draft on the reference grid) in both engines so
        # proposal streams are identical and any divergence is the
        # verify matmuls.
        cfg = TINY
        params = init_params(cfg, jax.random.PRNGKey(6))
        qp = quantize_params(params)
        dq = dequantize_params(qp, jnp.float32)
        mk = dict(n_slots=2, cache_len=128, block_size=16,
                  prefill_chunk_blocks=0, spec_draft=(dq, cfg),
                  spec_k=4)
        ref = ContinuousEngine(dq, cfg, **mk)
        got = ContinuousEngine(qp, cfg, weight_dtype="int8", **mk)
        rng = np.random.default_rng(14)
        prompts = [rng.integers(0, cfg.vocab_size, 9).tolist()]
        want = self._run(ref, prompts, 24)
        have = self._run(got, prompts, 24)
        assert want == have
        assert got.scheduler_stats()["spec_draft_tokens"] > 0

    @pytest.mark.slow
    def test_greedy_identity_tp2(self):
        # tp=2 on the virtual mesh: quantized leaves shard via
        # expand_quant_specs (qw on the weight's spec, scale on the out
        # axis) and the forward takes the GSPMD-partitionable dense
        # dequant path — tokens must still match the same-grid ref
        cfg, ref, got = self._engines(tp=2, cache_len=64, block_size=8)
        rng = np.random.default_rng(15)
        prompts = [rng.integers(0, cfg.vocab_size, 12).tolist()]
        assert self._run(ref, prompts, 16) == self._run(got, prompts, 16)


class TestCheckpointWeightDtype:
    def test_save_restore_quantized_lossless(self, tmp_path):
        ocp = pytest.importorskip("orbax.checkpoint")  # noqa: F841
        from kubeinfer_tpu.inference.checkpoint import (
            restore_checkpoint, save_checkpoint,
        )

        params = init_params(TINY, jax.random.PRNGKey(2),
                             weight_dtype="int8")
        save_checkpoint(str(tmp_path / "ck"), params, TINY, step=7)
        import json
        meta = json.loads((tmp_path / "ck" / "meta.json").read_text())
        assert meta["weight_dtype"] == "int8"
        back, cfg, step = restore_checkpoint(str(tmp_path / "ck"))
        assert step == 7
        # bit-lossless: identical int8 codes and f32 scales — restore
        # must NEVER re-quantize (that would re-derive scales from the
        # codes and corrupt silently)
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # restored tree drops straight into an int8 engine: the held
        # dtype matches the request, so the double-quantize guard is
        # a no-op, not a trip
        eng = ContinuousEngine(back, cfg, n_slots=2, cache_len=64,
                               block_size=8, weight_dtype="int8")
        assert eng.weight_dtype == "int8"

    def test_bf16_meta_default(self, tmp_path):
        ocp = pytest.importorskip("orbax.checkpoint")  # noqa: F841
        from kubeinfer_tpu.inference.checkpoint import (
            restore_checkpoint, save_checkpoint,
        )

        params = init_params(TINY, jax.random.PRNGKey(2))
        save_checkpoint(str(tmp_path / "ck"), params, TINY)
        import json
        meta = json.loads((tmp_path / "ck" / "meta.json").read_text())
        assert meta["weight_dtype"] == "bf16"
        back, _, _ = restore_checkpoint(str(tmp_path / "ck"))
        assert params_weight_dtype(back) == "bf16"


class TestQueueDepthShedding:
    def test_submit_sheds_past_limit(self):
        params = init_params(TINY, jax.random.PRNGKey(0))
        # engine deliberately NOT started: submits queue up, which is
        # exactly the state the limit exists to refuse at
        eng = ContinuousEngine(params, TINY, n_slots=2, cache_len=64,
                               block_size=8, queue_depth_limit=2)
        assert eng.queue_depth_limit == 2
        eng.submit([1, 2, 3], max_new_tokens=4)
        eng.submit([1, 2, 3], max_new_tokens=4)
        with pytest.raises(EngineOverloadedError) as ei:
            eng.submit([1, 2, 3], max_new_tokens=4)
        assert ei.value.retry_after_s > 0
        # the refusal is ledgered as the SPEC's queued self-loop then
        # the terminal: submit -> backpressure -> fail(shed)
        evs = eng.flight.snapshot()
        kinds = [e.kind for e in evs]
        i = kinds.index("backpressure")
        bp = evs[i]
        assert bp.detail["reason"] == "queue_depth_limit"
        assert bp.detail["limit"] == 2
        fail = next(e for e in evs[i:] if e.kind == "fail")
        assert fail.detail["reason"] == "shed"
        assert eng.stats_summary()["weight_dtype"] == "bf16"

    def test_server_responds_503_with_retry_after(self):
        import urllib.error
        import urllib.request

        from kubeinfer_tpu.inference.engine import Engine
        from kubeinfer_tpu.inference.server import InferenceServer

        params = init_params(TINY, jax.random.PRNGKey(0))
        eng = ContinuousEngine(params, TINY, n_slots=2, cache_len=64,
                               block_size=8, queue_depth_limit=1)
        # one queued request fills the depth budget (engine not
        # started, so it stays queued); the HTTP request must then be
        # refused fast with the backoff hint, not enqueued behind it
        eng.submit([1, 2, 3], max_new_tokens=4)
        srv = InferenceServer(Engine(params, TINY), model_id="tiny",
                              port=0, continuous=eng).start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/v1/completions",
                data=b'{"prompt": [1, 2, 3], "max_tokens": 2}',
                method="POST",
                headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=30)
            assert ei.value.code == 503
            assert int(ei.value.headers["Retry-After"]) >= 1
            import json
            body = json.loads(ei.value.read())
            assert body["error"]["type"] == "overloaded"
            out = srv.registry.render()
            assert ('kubeinfer_requests_shed_total'
                    '{reason="queue_depth_limit"} 1') in out
        finally:
            srv.stop()
            eng.stop()
