"""Analyzer self-tests + the tier-1 gate (ISSUE 2 acceptance).

Fixture snippets inject one violation per rule and assert the analyzer
catches exactly it; known-good twins assert the matching idiom stays
clean (the false-positive budget is zero — a noisy linter gets
suppressed wholesale and stops being a gate). The final test runs the
real analyzer over the real repo surface and asserts zero unsuppressed
findings, which is what makes `make lint` failures reproduce in tier-1.
"""

from __future__ import annotations

import subprocess
import sys
import textwrap
import threading
from pathlib import Path

import pytest

from kubeinfer_tpu.analysis import racecheck
from kubeinfer_tpu.analysis.core import analyze_paths, analyze_source

REPO = Path(__file__).resolve().parent.parent


def run_src(src: str, path: str = "pkg/sample.py", **kw):
    return analyze_source(textwrap.dedent(src), path, **kw)


def rules_of(findings):
    return [f.rule for f in findings]


# --- jit-host-sync ----------------------------------------------------------


def test_item_inside_jit_flagged():
    fs = run_src(
        """
        import jax

        @jax.jit
        def f(x):
            return x.item()
        """
    )
    assert rules_of(fs) == ["jit-host-sync"]


def test_int_cast_on_traced_flagged_static_arg_clean():
    fs = run_src(
        """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("n",))
        def f(x, n):
            k = int(n)       # static: resolved at trace time
            y = int(x + 1)   # traced: crashes under trace
            return k + y
        """
    )
    assert len(fs) == 1 and fs[0].rule == "jit-host-sync"
    assert "int()" in fs[0].message


def test_np_asarray_of_traced_inside_jit_flagged():
    fs = run_src(
        """
        import jax, numpy as np

        @jax.jit
        def f(x):
            return np.asarray(x)
        """
    )
    assert rules_of(fs) == ["jit-host-sync"]


def test_device_get_inside_jit_flagged():
    fs = run_src(
        """
        import jax

        @jax.jit
        def f(x):
            return jax.device_get(x)
        """
    )
    assert rules_of(fs) == ["jit-host-sync"]


def test_shape_read_is_clean():
    fs = run_src(
        """
        import jax, jax.numpy as jnp

        @jax.jit
        def f(x):
            b = x.shape[0]          # static metadata, not data
            return jnp.zeros((b, int(x.ndim)))
        """
    )
    assert fs == []


def test_closure_constant_is_trace_time():
    # float() of a module-level jnp constant is legal inside jit: the
    # closure is concrete at trace time (solver INFEASIBLE pattern)
    fs = run_src(
        """
        import jax, jax.numpy as jnp

        BIG = jnp.float32(1e9)

        @jax.jit
        def f(x):
            return x * float(BIG)
        """
    )
    assert fs == []


# --- jit-traced-branch ------------------------------------------------------


def test_if_on_traced_flagged():
    fs = run_src(
        """
        import jax, jax.numpy as jnp

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
        """
    )
    assert rules_of(fs) == ["jit-traced-branch"]


def test_while_on_traced_flagged():
    fs = run_src(
        """
        import jax

        @jax.jit
        def f(x):
            while x < 10:
                x = x + 1
            return x
        """
    )
    assert rules_of(fs) == ["jit-traced-branch"]


def test_is_none_branch_clean():
    fs = run_src(
        """
        import jax, jax.numpy as jnp

        @jax.jit
        def f(x, key=None):
            if key is None:
                key = jax.random.PRNGKey(0)
            return x, key
        """
    )
    assert fs == []


def test_branch_on_static_arg_clean():
    fs = run_src(
        """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("flag",))
        def f(x, flag):
            if flag:
                return x * 2
            return x
        """
    )
    assert fs == []


# --- jit-dynamic-shape ------------------------------------------------------


def test_nonzero_without_size_flagged_with_size_clean():
    fs = run_src(
        """
        import jax, jax.numpy as jnp

        @jax.jit
        def f(x):
            bad = jnp.nonzero(x)
            ok = jnp.nonzero(x, size=8, fill_value=-1)
            return bad, ok
        """
    )
    assert rules_of(fs) == ["jit-dynamic-shape"]


def test_unique_flagged():
    fs = run_src(
        """
        import jax, jax.numpy as jnp

        @jax.jit
        def f(x):
            return jnp.unique(x)
        """
    )
    assert rules_of(fs) == ["jit-dynamic-shape"]


def test_boolean_mask_index_flagged_where_clean():
    fs = run_src(
        """
        import jax, jax.numpy as jnp

        @jax.jit
        def f(x):
            bad = x[x > 0]
            ok = jnp.where(x > 0, x, 0.0)   # three-arg where is static
            return bad, ok
        """
    )
    assert rules_of(fs) == ["jit-dynamic-shape"]


def test_single_arg_where_flagged():
    fs = run_src(
        """
        import jax, jax.numpy as jnp

        @jax.jit
        def f(x):
            return jnp.where(x > 0)
        """
    )
    assert rules_of(fs) == ["jit-dynamic-shape"]


def test_per_row_cache_scatter_clean():
    # the ragged-decode cache write (model.decoder_layer): the batched
    # .at[rows, offset].set scatter and the vmapped per-row
    # dynamic_update_slice are both static-shape — traced values feed
    # the INDICES, never the output shape
    fs = run_src(
        """
        import jax, jax.numpy as jnp

        @jax.jit
        def f(cache, new, offset):
            rows = jnp.arange(cache.shape[0])
            ck = cache.at[rows, offset].set(new[:, 0])
            cv = jax.vmap(
                lambda c, n, o: jax.lax.dynamic_update_slice(
                    c, n, (o, 0, 0)
                )
            )(cache, new, offset)
            return ck, cv
        """
    )
    assert fs == []


# --- host-sync boundary rule ------------------------------------------------


def test_jit_result_readback_flagged_outside_jit():
    fs = run_src(
        """
        import jax, numpy as np

        @jax.jit
        def step(x):
            return x * 2

        def serve(x):
            y = step(x)
            return np.asarray(y)
        """
    )
    assert rules_of(fs) == ["host-sync"]


def test_boundary_rule_off_for_test_files():
    src = """
        import jax, numpy as np

        @jax.jit
        def step(x):
            return x * 2

        def test_step():
            assert np.asarray(step(1.0)) == 2.0
        """
    assert run_src(src, path="tests/test_sample.py") == []
    assert rules_of(run_src(src, path="pkg/mod.py")) == ["host-sync"]


def test_cross_file_jit_registry():
    # bench.py pattern: the jit decorator lives in another file; the
    # caller must still see a device value
    fs = run_src(
        """
        import numpy as np
        from pkg.solver import solve

        def bench():
            out = solve(1.0)
            return np.asarray(out)
        """,
        jit_registry={"solve": (frozenset(), frozenset())},
    )
    assert rules_of(fs) == ["host-sync"]


# --- suppressions -----------------------------------------------------------


def test_allow_same_line_suppresses():
    fs = run_src(
        """
        import jax

        @jax.jit
        def f(x):
            return x.item()  # lint: allow[jit-host-sync] fixture: deliberate
        """
    )
    assert fs == []


def test_allow_preceding_comment_line_suppresses():
    fs = run_src(
        """
        import jax

        @jax.jit
        def f(x):
            # lint: allow[jit-host-sync] fixture: deliberate sync
            return x.item()
        """
    )
    assert fs == []


def test_bare_allow_is_a_finding():
    fs = run_src(
        """
        import jax

        @jax.jit
        def f(x):
            return x.item()  # lint: allow[jit-host-sync]
        """
    )
    assert rules_of(fs) == ["lint-bare-allow"]


def test_unknown_rule_in_allow_is_a_finding():
    fs = run_src("x = 1  # lint: allow[no-such-rule] reason here\n")
    assert rules_of(fs) == ["lint-unknown-rule"]


def test_allow_in_docstring_is_not_a_suppression():
    fs = run_src(
        '''
        def f():
            """Docs may mention `# lint: allow[jit-host-sync]` freely."""
            return 1
        '''
    )
    assert fs == []


def test_allow_only_matches_named_rule():
    fs = run_src(
        """
        import jax

        @jax.jit
        def f(x):
            return x.item()  # lint: allow[jit-dynamic-shape] wrong rule named
        """
    )
    # the misnamed allow now ALSO surfaces as a stale suppression: the
    # named rule never fires on that line
    assert rules_of(fs) == ["jit-host-sync", "unused-suppression"]


# --- lock-discipline --------------------------------------------------------


def test_unlocked_write_flagged():
    fs = run_src(
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def locked_inc(self):
                with self._lock:
                    self._n += 1

            def racy_inc(self):
                self._n += 1
        """
    )
    assert rules_of(fs) == ["lock-discipline"]
    assert "racy_inc" in fs[0].message


def test_init_writes_and_all_locked_clean():
    fs = run_src(
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
                self._replay()

            def _replay(self):
                # reachable only from __init__: pre-sharing writes
                self._n = 10

            def inc(self):
                with self._lock:
                    self._n += 1
        """
    )
    assert fs == []


def test_always_locked_helper_propagates():
    # batching._admit shape: helper's own body shows no lock, but every
    # call site holds it
    fs = run_src(
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def _bump(self):
                self._n += 1

            def inc(self):
                with self._lock:
                    self._bump()

            def inc2(self):
                with self._lock:
                    self._bump()
        """
    )
    assert fs == []


def test_mutator_call_counts_as_write():
    fs = run_src(
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def locked_add(self, x):
                with self._lock:
                    self._items.append(x)

            def racy_add(self, x):
                self._items.append(x)
        """
    )
    assert rules_of(fs) == ["lock-discipline"]


def test_event_methods_are_exempt():
    # threading.Event is internally synchronized; set/clear anywhere is
    # fine even if one call site happens to hold a lock
    fs = run_src(
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._flag = threading.Event()

            def locked_set(self):
                with self._lock:
                    self._flag.set()

            def free_clear(self):
                self._flag.clear()
        """
    )
    assert fs == []


def test_module_level_global_discipline():
    fs = run_src(
        """
        import threading

        _lock = threading.Lock()
        _cache = None

        def fill():
            global _cache
            with _lock:
                _cache = 1

        def racy_fill():
            global _cache
            _cache = 2
        """
    )
    assert rules_of(fs) == ["lock-discipline"]
    assert "racy_fill" in fs[0].message


# --- racecheck runtime sentinel ---------------------------------------------


def test_make_lock_unarmed_is_plain(monkeypatch):
    monkeypatch.delenv("KUBEINFER_RACECHECK", raising=False)
    lk = racecheck.make_lock("t.plain")
    assert not isinstance(lk, racecheck.TrackedLock)
    with lk:
        pass


def test_make_lock_armed_is_tracked(monkeypatch):
    monkeypatch.setenv("KUBEINFER_RACECHECK", "1")
    lk = racecheck.make_lock("t.tracked")
    assert isinstance(lk, racecheck.TrackedLock)


def test_lock_order_inversion_reports_cycle(monkeypatch):
    monkeypatch.setenv("KUBEINFER_RACECHECK", "1")
    racecheck.REGISTRY.reset()
    a = racecheck.make_lock("t.A")
    b = racecheck.make_lock("t.B")
    with a:
        with b:
            pass
    with b:
        with a:  # inverted: the deadlock-potential edge
            pass
    cycles = racecheck.REGISTRY.cycles()
    assert cycles, "inverted acquisition order must produce a cycle"
    assert {"t.A", "t.B"} <= set(cycles[0])
    racecheck.REGISTRY.reset()


def test_consistent_order_is_acyclic(monkeypatch):
    monkeypatch.setenv("KUBEINFER_RACECHECK", "1")
    racecheck.REGISTRY.reset()
    a = racecheck.make_lock("t.A2")
    b = racecheck.make_lock("t.B2")
    for _ in range(3):
        with a:
            with b:
                pass
    assert racecheck.REGISTRY.cycles() == []
    rep = racecheck.REGISTRY.report()
    assert ("t.A2", "t.B2") in rep["edges"]
    assert rep["hold_max_s"]["t.A2"] >= 0.0
    racecheck.REGISTRY.reset()


def test_tracked_condition_wait_notify(monkeypatch):
    monkeypatch.setenv("KUBEINFER_RACECHECK", "1")
    racecheck.REGISTRY.reset()
    cond = racecheck.make_condition("t.cond")
    hits = []

    def waiter():
        with cond:
            while not hits:
                cond.wait(timeout=5.0)
            hits.append("woke")

    t = threading.Thread(target=waiter)
    t.start()
    with cond:
        hits.append("go")
        cond.notify()
    t.join(timeout=5.0)
    assert not t.is_alive() and hits == ["go", "woke"]
    racecheck.REGISTRY.reset()


def test_cross_thread_edges_detect_inversion(monkeypatch):
    monkeypatch.setenv("KUBEINFER_RACECHECK", "1")
    racecheck.REGISTRY.reset()
    a = racecheck.make_lock("t.X")
    b = racecheck.make_lock("t.Y")

    def t1():
        with a:
            with b:
                pass

    def t2():
        with b:
            with a:
                pass

    # run serially so both orders are observed without actually deadlocking
    th = threading.Thread(target=t1)
    th.start()
    th.join()
    th = threading.Thread(target=t2)
    th.start()
    th.join()
    assert racecheck.REGISTRY.cycles()
    racecheck.REGISTRY.reset()


# --- log-discipline ---------------------------------------------------------


def test_bare_print_in_library_flagged():
    fs = run_src(
        """
        def handler(x):
            print("served", x)
        """
    )
    assert rules_of(fs) == ["log-discipline"]


def test_basic_config_in_library_flagged():
    fs = run_src(
        """
        import logging

        def setup():
            logging.basicConfig(level=logging.INFO)
        """
    )
    assert rules_of(fs) == ["log-discipline"]


def test_module_logger_is_clean():
    fs = run_src(
        """
        import logging

        log = logging.getLogger(__name__)

        def handler(x):
            log.info("served %s", x)
        """
    )
    assert fs == []


def test_cli_entrypoints_exempt():
    src = """
    import logging

    def main():
        logging.basicConfig(level=logging.INFO)
        print("ready")
    """
    for path in ("pkg/__main__.py", "pkg/ctl.py", "bench.py",
                 "__graft_entry__.py", "scripts/tool.py",
                 "tests/test_thing.py"):
        assert run_src(src, path=path) == []
    assert rules_of(run_src(src, path="pkg/server.py")) == [
        "log-discipline", "log-discipline",
    ]


def test_shadowed_print_is_not_the_builtin():
    fs = run_src(
        """
        def render(print):
            print("not the builtin")

        class W:
            def print(self):
                pass

            def go(self):
                self.print()
        """
    )
    assert fs == []


def test_log_discipline_allow_suppresses():
    fs = run_src(
        """
        def main():
            # lint: allow[log-discipline] process entrypoint owns stdout
            print("ready")
        """
    )
    assert fs == []


# --- metric-name ------------------------------------------------------------


def test_metric_missing_prefix_flagged():
    fs = run_src(
        """
        from kubeinfer_tpu.metrics.registry import Counter

        c = Counter("requests_total", "requests")
        """
    )
    assert rules_of(fs) == ["metric-name"]
    assert "kubeinfer_" in fs[0].message


def test_counter_without_total_flagged():
    fs = run_src(
        """
        from kubeinfer_tpu.metrics.registry import Counter

        c = Counter("kubeinfer_requests", "requests")
        """
    )
    assert rules_of(fs) == ["metric-name"]
    assert "_total" in fs[0].message


def test_histogram_without_unit_flagged():
    fs = run_src(
        """
        from kubeinfer_tpu.metrics.registry import Histogram

        h = Histogram("kubeinfer_request_latency", "latency")
        """
    )
    assert rules_of(fs) == ["metric-name"]


def test_gauge_without_quantity_suffix_flagged():
    fs = run_src(
        """
        from kubeinfer_tpu.metrics.registry import Gauge

        g = Gauge("kubeinfer_goodput", "tokens per second")
        """
    )
    assert rules_of(fs) == ["metric-name"]


def test_computed_metric_name_flagged():
    fs = run_src(
        """
        from kubeinfer_tpu.metrics.registry import Counter

        def make(component):
            return Counter(f"kubeinfer_{component}_total", "per component")
        """
    )
    assert rules_of(fs) == ["metric-name"]
    assert "literal" in fs[0].message


def test_compliant_collectors_clean():
    fs = run_src(
        """
        from kubeinfer_tpu.metrics.registry import (
            Counter, Gauge, Histogram,
        )

        c = Counter("kubeinfer_requests_total", "requests")
        h = Histogram("kubeinfer_request_seconds", "latency")
        g1 = Gauge("kubeinfer_ready_replicas", "replicas")
        g2 = Gauge("kubeinfer_stale_seconds", "staleness")
        g3 = Gauge("kubeinfer_goodput_tokens_per_second", "goodput")
        """
    )
    assert fs == []


def test_collections_counter_not_matched():
    fs = run_src(
        """
        import collections

        hist = collections.Counter(["a", "b", "a"])
        """
    )
    assert fs == []


def test_metric_name_rule_off_for_test_files():
    src = """
    from kubeinfer_tpu.metrics.registry import Counter

    c = Counter("t_total", "fixture counter")
    """
    assert run_src(src, path="tests/test_metrics.py") == []
    assert rules_of(run_src(src, path="pkg/server.py")) == ["metric-name"]


# --- metric-label -----------------------------------------------------------


def test_label_case_and_high_cardinality_flagged():
    fs = run_src(
        """
        from kubeinfer_tpu.metrics.registry import Counter

        c = Counter("kubeinfer_req_total", "reqs",
                    labels=("Kind", "request_id"))
        """
    )
    assert rules_of(fs) == ["metric-label", "metric-label"]
    msgs = " ".join(f.message for f in fs)
    assert "'Kind'" in msgs and "high-cardinality" in msgs


def test_histogram_positional_labels_checked():
    # Histogram's constructor takes buckets as positional 2, pushing the
    # labels tuple to positional 3 — the pass must look there, not at 2.
    fs = run_src(
        """
        from kubeinfer_tpu.metrics.registry import Histogram

        h = Histogram("kubeinfer_wait_seconds", "wait", (0.1, 1.0),
                      ("trace_id",))
        """
    )
    assert rules_of(fs) == ["metric-label"]
    assert "high-cardinality" in fs[0].message


def test_computed_label_set_flagged_literal_clean():
    fs = run_src(
        """
        from kubeinfer_tpu.metrics.registry import Gauge

        LABELS = ("kind",)
        g = Gauge("kubeinfer_queue_depth", "depth", labels=LABELS)
        ok = Gauge("kubeinfer_pool_free", "free", labels=("kind", "node"))
        """
    )
    assert rules_of(fs) == ["metric-label"]
    assert "literal tuple/list" in fs[0].message


# --- blocking-under-lock ----------------------------------------------------


def test_sleep_under_lock_flagged_direct():
    fs = run_src(
        """
        import time
        from kubeinfer_tpu.analysis.racecheck import make_lock

        class Poller:
            def __init__(self):
                self._lock = make_lock("poller")

            def wait(self):
                with self._lock:
                    time.sleep(0.5)
        """
    )
    assert rules_of(fs) == ["blocking-under-lock"]
    assert "time.sleep()" in fs[0].message
    # direct findings land on the blocking line itself
    assert fs[0].line == 11


def test_transitive_block_lands_on_call_under_lock():
    fs = run_src(
        """
        import subprocess
        from kubeinfer_tpu.analysis.racecheck import make_lock

        class Builder:
            def __init__(self):
                self._lock = make_lock("builder")

            def _compile(self):
                subprocess.run(["cc", "x.c"])

            def build(self):
                with self._lock:
                    self._compile()
        """
    )
    assert rules_of(fs) == ["blocking-under-lock"]
    # the suppression/fix point is where the lock scope is chosen — the
    # call line — not the callee's subprocess line
    assert fs[0].line == 14
    assert "_compile()" in fs[0].message


def test_jit_dispatch_under_lock_flagged_via_registry():
    fs = run_src(
        """
        from kubeinfer_tpu.analysis.racecheck import make_lock

        class Engine:
            def __init__(self):
                self._lock = make_lock("engine")

            def admit(self, x):
                with self._lock:
                    return step_fn(x)
        """,
        jit_registry={"step_fn": frozenset()},
    )
    assert rules_of(fs) == ["blocking-under-lock"]
    assert "jit dispatch" in fs[0].message


def test_blocking_outside_lock_and_init_clean():
    fs = run_src(
        """
        import time
        from kubeinfer_tpu.analysis.racecheck import make_lock

        class Warmup:
            def __init__(self):
                self._lock = make_lock("warm")
                with self._lock:
                    # nothing shares the object mid-__init__
                    time.sleep(0.01)

            def tick(self):
                time.sleep(0.1)
                with self._lock:
                    self.n = 1
        """
    )
    assert fs == []


def test_blockcheck_off_for_test_files():
    src = """
    import time
    from kubeinfer_tpu.analysis.racecheck import make_lock

    _mu = make_lock("fixture")

    def poll():
        with _mu:
            time.sleep(0.01)
    """
    assert run_src(src, path="tests/test_fixture.py") == []
    assert rules_of(run_src(src, path="pkg/poll.py")) == [
        "blocking-under-lock"]


def test_blocking_under_lock_allow_suppresses():
    fs = run_src(
        """
        import time
        from kubeinfer_tpu.analysis.racecheck import make_lock

        class S:
            def __init__(self):
                self._lock = make_lock("s")

            def settle(self):
                with self._lock:
                    # lint: allow[blocking-under-lock] 10ms debounce is the accepted ceiling
                    time.sleep(0.01)
        """
    )
    assert fs == []


# --- unused-suppression -----------------------------------------------------


def test_stale_allow_is_a_finding():
    fs = run_src(
        """
        # lint: allow[jit-host-sync] left behind after a refactor
        x = 1
        """
    )
    assert rules_of(fs) == ["unused-suppression"]
    # lands on the comment's own line — that's the line to delete
    assert fs[0].line == 2
    assert "allow[jit-host-sync]" in fs[0].message


def test_consumed_allow_is_not_stale():
    fs = run_src(
        """
        import jax

        @jax.jit
        def f(x):
            # lint: allow[jit-host-sync] fixture: proving consumption
            return x.item()
        """
    )
    assert fs == []


def test_unused_suppression_is_unsuppressable():
    # allow[unused-suppression] neither hides the stale finding nor is
    # itself exempt from staleness — both comment lines get reported
    fs = run_src(
        """
        # lint: allow[unused-suppression] trying to hide staleness
        # lint: allow[metric-name] stale after rename
        x = 1
        """
    )
    assert rules_of(fs) == ["unused-suppression", "unused-suppression"]


def test_bare_and_unknown_allows_not_double_reported():
    # bare/unknown allows already carry their own meta finding; the
    # staleness pass must not pile a second finding on the same comment
    fs = run_src(
        """
        # lint: allow[jit-host-sync]
        x = 1
        y = 2  # lint: allow[not-a-rule] reasoned but bogus
        """
    )
    assert rules_of(fs) == ["lint-bare-allow", "lint-unknown-rule"]


# --- racecheck reservoir + cycle determinism --------------------------------


def test_hold_stats_reservoir_bounded_and_deterministic():
    a = racecheck._HoldStats("pool.lock")
    b = racecheck._HoldStats("pool.lock")
    for i in range(500):
        a.add(float(i))
        b.add(float(i))
    assert a.count == 500
    assert a.max == 499.0
    assert len(a.samples) == a.CAP
    # name-seeded replacement RNG: which samples survive is a pure
    # function of the duration sequence, so two identical runs agree
    assert a.samples == b.samples
    # a different lock name seeds differently (same sequence, different
    # survivors) — proves the seed actually comes from the name
    c = racecheck._HoldStats("store.lock")
    for i in range(500):
        c.add(float(i))
    assert c.samples != a.samples


def test_cycle_report_independent_of_edge_insertion_order():
    def build(order):
        reg = racecheck._Registry()
        locks = {n: racecheck.TrackedLock(n) for n in "abc"}
        for outer, inner in order:
            reg.on_acquired(locks[outer])
            reg.on_acquired(locks[inner])
            reg.on_released(locks[inner])
            reg.on_released(locks[outer])
        return reg.cycles()

    fwd = build([("a", "b"), ("b", "c"), ("c", "a")])
    rev = build([("c", "a"), ("b", "c"), ("a", "b")])
    assert fwd == rev == [["a", "b", "c", "a"]]


# --- the tier-1 gate --------------------------------------------------------


def test_repo_surface_has_zero_unsuppressed_findings():
    paths = [REPO / p for p in
             ("kubeinfer_tpu", "tests", "scripts", "bench.py",
              "__graft_entry__.py")]
    findings, nfiles = analyze_paths([p for p in paths if p.exists()])
    assert nfiles > 50, "scan surface collapsed — path wiring broke"
    msgs = "\n".join(f.render() for f in findings)
    assert not findings, f"unsuppressed analysis findings:\n{msgs}"


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n\n@jax.jit\ndef f(x):\n    return x.item()\n"
    )
    proc = subprocess.run(
        [sys.executable, "-m", "kubeinfer_tpu.analysis", str(bad)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 1
    # grep/editor-clickable format: file:line rule message
    assert f"{bad}:5 jit-host-sync" in proc.stdout
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    proc = subprocess.run(
        [sys.executable, "-m", "kubeinfer_tpu.analysis", str(good)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0
