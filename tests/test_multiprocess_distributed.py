"""True multi-process distributed test: two OS processes, one jax
process group, one global mesh, one sharded solve.

This is the integration the single-process tests cannot give: separate
XLA clients coordinating through jax.distributed (the DCN topology's
shape, minus the second physical host). Workers run with scrubbed env so
the box's axon sitecustomize cannot wedge them.
"""

from __future__ import annotations

import os
import re
import socket
import subprocess
import sys

import pytest

WORKER = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "testdata", "distributed_worker.py",
)


@pytest.mark.slow
def test_two_process_group_runs_sharded_solve():
    from tests.conftest import scrubbed_pythonpath

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # 1 device per process; mesh spans processes
    env["PYTHONPATH"] = scrubbed_pythonpath()

    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(rank), "2", str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for rank in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        # a wedged worker (e.g. lost coordinator port) must not orphan
        # the pair holding the port past the test
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
    placed = set()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        m = re.search(rf"rank {rank}: placed (\d+)", out)
        assert m, f"rank {rank} output unparseable:\n{out}"
        placed.add(int(m.group(1)))
    # SPMD: both processes computed the same global result
    assert len(placed) == 1, outs
