"""Native-runtime model correctness, pinned against HF transformers.

The gold test: identical weights in our pure-JAX llama and HF's
LlamaForCausalLM (torch CPU) must produce matching logits. Everything
else (KV-cache decode, GQA, RoPE offsets) is checked for
self-consistency against the no-cache forward.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeinfer_tpu.inference import ModelConfig, PRESETS, forward, init_params
from kubeinfer_tpu.inference.weights import params_from_state_dict

TINY = PRESETS["tiny"]


def tokens_for(cfg: ModelConfig, B=2, T=12, seed=0) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(0, cfg.vocab_size, (B, T)).astype(np.int32)


class TestForwardBasics:
    def test_shapes_and_dtype(self):
        params = init_params(TINY, jax.random.PRNGKey(0))
        toks = jnp.asarray(tokens_for(TINY))
        logits, _ = forward(params, toks, TINY)
        assert logits.shape == (2, 12, TINY.vocab_size)
        assert logits.dtype == jnp.float32

    def test_causality(self):
        """Changing a future token must not affect earlier logits."""
        params = init_params(TINY, jax.random.PRNGKey(0))
        toks = tokens_for(TINY)
        logits1, _ = forward(params, jnp.asarray(toks), TINY)
        toks2 = toks.copy()
        toks2[:, -1] = (toks2[:, -1] + 1) % TINY.vocab_size
        logits2, _ = forward(params, jnp.asarray(toks2), TINY)
        np.testing.assert_allclose(
            np.asarray(logits1[:, :-1]), np.asarray(logits2[:, :-1]),
            rtol=1e-5, atol=1e-5,
        )
        assert not np.allclose(
            np.asarray(logits1[:, -1]), np.asarray(logits2[:, -1])
        )

    def test_gqa_vs_mha_differ_only_by_config(self):
        # smoke: GQA config (kv < heads) runs and produces finite logits
        params = init_params(TINY, jax.random.PRNGKey(1))
        logits, _ = forward(params, jnp.asarray(tokens_for(TINY)), TINY)
        assert np.isfinite(np.asarray(logits)).all()


class TestKVCacheDecode:
    @pytest.mark.slow
    def test_incremental_decode_matches_full_forward(self):
        """Prefill + per-token cached decode == one full forward."""
        cfg = TINY
        params = init_params(cfg, jax.random.PRNGKey(2))
        B, T_total, T_prefill = 2, 10, 6
        toks = tokens_for(cfg, B=B, T=T_total, seed=3)
        full_logits, _ = forward(params, jnp.asarray(toks), cfg)

        S = 16  # cache capacity
        caches = [
            (
                jnp.zeros((B, S, cfg.num_key_value_heads, cfg.head_dim)),
                jnp.zeros((B, S, cfg.num_key_value_heads, cfg.head_dim)),
            )
            for _ in range(cfg.num_hidden_layers)
        ]
        # prefill: causal over the prompt, cache cols beyond prompt masked
        pre = jnp.asarray(toks[:, :T_prefill])
        mask = jnp.zeros((B, T_prefill, S), bool)
        mask = mask.at[:, :, :T_prefill].set(
            jnp.tril(jnp.ones((T_prefill, T_prefill), bool))[None]
        )
        logits, caches = forward(
            params, pre, cfg, attn_mask=mask, kv_caches=caches, cache_offset=0
        )
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full_logits[:, :T_prefill]),
            rtol=2e-4, atol=2e-4,
        )

        # decode one token at a time
        for t in range(T_prefill, T_total):
            step = jnp.asarray(toks[:, t : t + 1])
            mask = (jnp.arange(S) <= t)[None, None, :]
            mask = jnp.broadcast_to(mask, (B, 1, S))
            logits, caches = forward(
                params, step, cfg, attn_mask=mask, kv_caches=caches,
                cache_offset=t,
            )
            np.testing.assert_allclose(
                np.asarray(logits[:, 0]), np.asarray(full_logits[:, t]),
                rtol=2e-4, atol=2e-4,
            )


class TestHFParity:
    @pytest.fixture(scope="class")
    def hf_model(self):
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")
        hf_cfg = transformers.LlamaConfig(
            vocab_size=TINY.vocab_size,
            hidden_size=TINY.hidden_size,
            intermediate_size=TINY.intermediate_size,
            num_hidden_layers=TINY.num_hidden_layers,
            num_attention_heads=TINY.num_attention_heads,
            num_key_value_heads=TINY.num_key_value_heads,
            rms_norm_eps=TINY.rms_norm_eps,
            rope_theta=TINY.rope_theta,
            max_position_embeddings=TINY.max_position_embeddings,
            tie_word_embeddings=False,
            attention_bias=False,
            mlp_bias=False,
        )
        torch.manual_seed(0)
        model = transformers.LlamaForCausalLM(hf_cfg).eval()
        return torch, model

    def test_logits_match_transformers(self, hf_model):
        torch, model = hf_model
        sd = model.state_dict()
        params = params_from_state_dict(sd, TINY, dtype=jnp.float32)

        toks = tokens_for(TINY, B=2, T=16, seed=7)
        with torch.no_grad():
            ref = model(torch.from_numpy(toks.astype(np.int64))).logits.numpy()
        ours, _ = forward(params, jnp.asarray(toks), TINY)
        np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4, atol=2e-4)

    def test_greedy_next_tokens_match(self, hf_model):
        torch, model = hf_model
        params = params_from_state_dict(model.state_dict(), TINY, jnp.float32)
        toks = tokens_for(TINY, B=3, T=9, seed=11)
        with torch.no_grad():
            ref = model(torch.from_numpy(toks.astype(np.int64))).logits.numpy()
        ours, _ = forward(params, jnp.asarray(toks), TINY)
        np.testing.assert_array_equal(
            np.asarray(ours[:, -1].argmax(-1)), ref[:, -1].argmax(-1)
        )


class TestQwen2Parity:
    """Qwen2 family: QKV biases (o bias-free), same decoder otherwise."""

    TINY_QWEN = ModelConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        rms_norm_eps=1e-6,
        rope_theta=10000.0,
        max_position_embeddings=512,
        qkv_bias=True,
    )

    @pytest.fixture(scope="class")
    def hf_qwen(self):
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")
        cfg = self.TINY_QWEN
        hf_cfg = transformers.Qwen2Config(
            vocab_size=cfg.vocab_size,
            hidden_size=cfg.hidden_size,
            intermediate_size=cfg.intermediate_size,
            num_hidden_layers=cfg.num_hidden_layers,
            num_attention_heads=cfg.num_attention_heads,
            num_key_value_heads=cfg.num_key_value_heads,
            rms_norm_eps=cfg.rms_norm_eps,
            rope_theta=cfg.rope_theta,
            max_position_embeddings=cfg.max_position_embeddings,
            tie_word_embeddings=False,
            # keep full attention: sliding window is a Qwen2 option our
            # runtime does not implement
            use_sliding_window=False,
        )
        torch.manual_seed(0)
        return torch, transformers.Qwen2ForCausalLM(hf_cfg).eval()

    def test_logits_match_transformers(self, hf_qwen):
        torch, model = hf_qwen
        params = params_from_state_dict(
            model.state_dict(), self.TINY_QWEN, dtype=jnp.float32
        )
        assert "q_bias" in params["layers"][0]  # biases actually loaded
        toks = tokens_for(self.TINY_QWEN, B=2, T=16, seed=3)
        with torch.no_grad():
            ref = model(torch.from_numpy(toks.astype(np.int64))).logits.numpy()
        ours, _ = forward(params, jnp.asarray(toks), self.TINY_QWEN)
        np.testing.assert_allclose(
            np.asarray(ours), ref, rtol=2e-4, atol=2e-4
        )

    def test_from_hf_dict_flags_qwen2(self):
        cfg = ModelConfig.from_hf_dict(
            {
                "model_type": "qwen2",
                "vocab_size": 256,
                "hidden_size": 64,
                "intermediate_size": 128,
                "num_hidden_layers": 2,
                "num_attention_heads": 4,
            }
        )
        assert cfg.qkv_bias

    def test_init_and_decode_roundtrip(self):
        # init_params layout matches forward's expectations with biases
        params = init_params(self.TINY_QWEN, jax.random.PRNGKey(1))
        toks = jnp.asarray(tokens_for(self.TINY_QWEN, B=1, T=8))
        logits, _ = forward(params, toks, self.TINY_QWEN)
        assert logits.shape == (1, 8, 256)

    def test_llama_attention_bias_rejected(self):
        # o_proj bias would be silently dropped by the loader; config
        # construction must refuse instead (r2 review finding)
        with pytest.raises(ValueError, match="attention_bias"):
            ModelConfig.from_hf_dict(
                {
                    "model_type": "llama",
                    "attention_bias": True,
                    "vocab_size": 256,
                    "hidden_size": 64,
                    "intermediate_size": 128,
                    "num_hidden_layers": 2,
                    "num_attention_heads": 4,
                }
            )


class TestMixtralParity:
    """Mixtral family: top-k routed SwiGLU experts replacing the MLP."""

    TINY_MIX = ModelConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=96,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        max_position_embeddings=512,
        num_local_experts=4,
        num_experts_per_tok=2,
    )

    @pytest.fixture(scope="class")
    def hf_mixtral(self):
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")
        cfg = self.TINY_MIX
        hf_cfg = transformers.MixtralConfig(
            vocab_size=cfg.vocab_size,
            hidden_size=cfg.hidden_size,
            intermediate_size=cfg.intermediate_size,
            num_hidden_layers=cfg.num_hidden_layers,
            num_attention_heads=cfg.num_attention_heads,
            num_key_value_heads=cfg.num_key_value_heads,
            rms_norm_eps=cfg.rms_norm_eps,
            rope_theta=cfg.rope_theta,
            max_position_embeddings=cfg.max_position_embeddings,
            num_local_experts=cfg.num_local_experts,
            num_experts_per_tok=cfg.num_experts_per_tok,
            tie_word_embeddings=False,
        )
        torch.manual_seed(0)
        return torch, transformers.MixtralForCausalLM(hf_cfg).eval()

    def test_logits_match_transformers(self, hf_mixtral):
        # routing parity note: HF softmaxes all logits then renormalizes
        # the top-k; ours softmaxes the top-k-masked logits — identical
        # by algebra (the full-softmax denominator cancels)
        torch, model = hf_mixtral
        params = params_from_state_dict(
            model.state_dict(), self.TINY_MIX, dtype=jnp.float32
        )
        assert "moe" in params["layers"][0]
        toks = tokens_for(self.TINY_MIX, B=2, T=12, seed=13)
        with torch.no_grad():
            ref = model(torch.from_numpy(toks.astype(np.int64))).logits.numpy()
        ours, _ = forward(params, jnp.asarray(toks), self.TINY_MIX)
        np.testing.assert_allclose(
            np.asarray(ours), ref, rtol=3e-4, atol=3e-4
        )

    def test_hf_dict_roundtrip(self):
        cfg = ModelConfig.from_hf_dict(
            {
                "model_type": "mixtral",
                "vocab_size": 256,
                "hidden_size": 64,
                "intermediate_size": 96,
                "num_hidden_layers": 2,
                "num_attention_heads": 4,
                "num_key_value_heads": 2,
                "num_local_experts": 4,
                "num_experts_per_tok": 2,
            }
        )
        assert cfg.num_local_experts == 4 and cfg.num_experts_per_tok == 2

    def test_init_and_generate(self):
        # init layout matches forward; engine decode works with MoE layers
        from kubeinfer_tpu.inference.engine import Engine

        params = init_params(self.TINY_MIX, jax.random.PRNGKey(2))
        engine = Engine(params, self.TINY_MIX)
        out = engine.generate([[1, 2, 3, 4]], max_new_tokens=3)
        assert out.tokens.shape == (1, 3)


class TestGemmaParity:
    """Gemma family: tied embeddings scaled by sqrt(H) into the residual
    stream, tanh-approx GeGLU, offset RMSNorm (gain = 1 + w), MQA."""

    # the exact config the demo/e2e path serves — parity must cover it,
    # not a drift-prone test-local copy
    TINY_GEMMA = PRESETS["tiny-gemma"]

    @pytest.fixture(scope="class")
    def hf_gemma(self):
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")
        cfg = self.TINY_GEMMA
        hf_cfg = transformers.GemmaConfig(
            vocab_size=cfg.vocab_size,
            hidden_size=cfg.hidden_size,
            intermediate_size=cfg.intermediate_size,
            num_hidden_layers=cfg.num_hidden_layers,
            num_attention_heads=cfg.num_attention_heads,
            num_key_value_heads=cfg.num_key_value_heads,
            # explicit: GemmaConfig defaults head_dim to 256 regardless
            # of hidden_size/heads
            head_dim=cfg.head_dim,
            rms_norm_eps=cfg.rms_norm_eps,
            rope_theta=cfg.rope_theta,
            max_position_embeddings=cfg.max_position_embeddings,
            tie_word_embeddings=True,
            hidden_act="gelu_pytorch_tanh",
            hidden_activation="gelu_pytorch_tanh",
        )
        torch.manual_seed(0)
        return torch, transformers.GemmaForCausalLM(hf_cfg).eval()

    def test_logits_match_transformers(self, hf_gemma):
        torch, model = hf_gemma
        params = params_from_state_dict(
            model.state_dict(), self.TINY_GEMMA, dtype=jnp.float32
        )
        assert "lm_head" not in params  # tied
        toks = tokens_for(self.TINY_GEMMA, B=2, T=16, seed=5)
        with torch.no_grad():
            ref = model(torch.from_numpy(toks.astype(np.int64))).logits.numpy()
        ours, _ = forward(params, jnp.asarray(toks), self.TINY_GEMMA)
        np.testing.assert_allclose(
            np.asarray(ours), ref, rtol=2e-4, atol=2e-4
        )

    def test_from_hf_dict_flags_gemma(self):
        cfg = ModelConfig.from_hf_dict(
            {
                "model_type": "gemma",
                "vocab_size": 256000,
                "hidden_size": 2048,
                "intermediate_size": 16384,
                "num_hidden_layers": 18,
                "num_attention_heads": 8,
                "num_key_value_heads": 1,
            }
        )
        assert cfg.tie_word_embeddings
        assert cfg.scale_embeddings
        assert cfg.rmsnorm_offset
        assert cfg.hidden_act == "gelu_pytorch_tanh"

    def test_generate_smoke(self):
        """The engine stack (prefill + decode cache) runs the gemma
        config end to end — catches family-specific shape breaks (MQA
        n_kv=1, tied head) outside the pure forward."""
        from kubeinfer_tpu.inference.engine import Engine

        params = init_params(self.TINY_GEMMA, jax.random.PRNGKey(1))
        eng = Engine(params, self.TINY_GEMMA, max_cache_len=64)
        out = eng.generate([[3, 5, 7, 9]], max_new_tokens=6)
        assert out.tokens.shape == (1, 6)
        assert out.lengths[0] == 6

    def test_rectangular_head_dim_matches_transformers(self):
        """gemma-7b's geometry: head_dim overridden (heads*head_dim !=
        hidden), making q/o projections rectangles — pinned against HF
        with the same override."""
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")
        cfg = ModelConfig(
            vocab_size=256,
            hidden_size=48,
            intermediate_size=96,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            head_dim_override=16,  # 4 x 16 = 64-wide q/o on 48 hidden
            rms_norm_eps=1e-6,
            max_position_embeddings=512,
            tie_word_embeddings=True,
            hidden_act="gelu_pytorch_tanh",
            scale_embeddings=True,
            rmsnorm_offset=True,
        )
        hf_cfg = transformers.GemmaConfig(
            vocab_size=cfg.vocab_size,
            hidden_size=cfg.hidden_size,
            intermediate_size=cfg.intermediate_size,
            num_hidden_layers=cfg.num_hidden_layers,
            num_attention_heads=cfg.num_attention_heads,
            num_key_value_heads=cfg.num_key_value_heads,
            head_dim=16,
            rms_norm_eps=cfg.rms_norm_eps,
            max_position_embeddings=cfg.max_position_embeddings,
            tie_word_embeddings=True,
            hidden_act="gelu_pytorch_tanh",
            hidden_activation="gelu_pytorch_tanh",
        )
        torch.manual_seed(2)
        model = transformers.GemmaForCausalLM(hf_cfg).eval()
        params = params_from_state_dict(
            model.state_dict(), cfg, dtype=jnp.float32
        )
        assert params["layers"][0]["q_proj"].shape == (48, 64)
        toks = tokens_for(cfg, B=1, T=12, seed=6)
        with torch.no_grad():
            ref = model(torch.from_numpy(toks.astype(np.int64))).logits.numpy()
        ours, _ = forward(params, jnp.asarray(toks), cfg)
        np.testing.assert_allclose(
            np.asarray(ours), ref, rtol=2e-4, atol=2e-4
        )

    def test_pipeline_forward_matches_dense(self):
        """pipeline_forward must carry the gemma flags too (embedding
        scale + offset final norm happen OUTSIDE decoder_layer there)."""
        from kubeinfer_tpu.inference.pipeline import (
            make_pp_mesh,
            pipeline_forward,
        )

        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        params = init_params(self.TINY_GEMMA, jax.random.PRNGKey(3))
        toks = tokens_for(self.TINY_GEMMA, B=2, T=8, seed=7)
        want, _ = forward(params, jnp.asarray(toks), self.TINY_GEMMA)
        mesh = make_pp_mesh(2)
        got = pipeline_forward(
            params, jnp.asarray(toks), self.TINY_GEMMA, mesh, n_microbatches=2
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want, np.float32),
            rtol=2e-4, atol=2e-4,
        )
