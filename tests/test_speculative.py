"""Speculative decoding correctness.

The contract: greedy speculative output is token-identical to vanilla
greedy decoding for ANY draft model — a good draft only changes the
cost, a bad draft only wastes speculation. Both directions are pinned:
a self-draft (acceptance 1.0) and a randomly initialized draft
(acceptance ~1/vocab), plus EOS handling and ragged batches.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from kubeinfer_tpu.inference import ModelConfig, PRESETS, init_params
from kubeinfer_tpu.inference.engine import Engine
from kubeinfer_tpu.inference.speculative import SpeculativeEngine

TINY = PRESETS["tiny"]
DRAFT_CFG = ModelConfig(
    vocab_size=TINY.vocab_size,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=1,
    num_attention_heads=2,
    num_key_value_heads=2,
    max_position_embeddings=TINY.max_position_embeddings,
)


@pytest.fixture(scope="module")
def target_params():
    return init_params(TINY, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def draft_params():
    return init_params(DRAFT_CFG, jax.random.PRNGKey(9))


def vanilla(target_params, prompts, max_new, eos_id=-1):
    return Engine(target_params, TINY).generate(
        prompts, max_new_tokens=max_new, eos_id=eos_id
    )


class TestGreedyEquivalence:
    def test_self_draft_exact(self, target_params):
        # draft == target: every draft token accepted, output identical
        prompts = [[3, 1, 4, 1, 5], [9, 2, 6]]
        ref = vanilla(target_params, prompts, 12)
        spec = SpeculativeEngine(
            target_params, TINY, target_params, TINY, k=4
        ).generate(prompts, max_new_tokens=12)
        np.testing.assert_array_equal(spec.tokens, ref.tokens)
        np.testing.assert_array_equal(spec.lengths, ref.lengths)

    def test_random_draft_exact(self, target_params, draft_params):
        # a draft that disagrees nearly always must still produce the
        # target's exact greedy output (just without speedup)
        prompts = [[7, 7, 7], [1, 2, 3, 4, 5, 6, 7, 8]]
        ref = vanilla(target_params, prompts, 10)
        spec = SpeculativeEngine(
            target_params, TINY, draft_params, DRAFT_CFG, k=3
        ).generate(prompts, max_new_tokens=10)
        np.testing.assert_array_equal(spec.tokens, ref.tokens)

    @pytest.mark.parametrize("k", [1, 2, 5])
    def test_speculation_depth_invariance(self, target_params, draft_params, k):
        prompts = [[5, 4, 3, 2]]
        ref = vanilla(target_params, prompts, 8)
        spec = SpeculativeEngine(
            target_params, TINY, draft_params, DRAFT_CFG, k=k
        ).generate(prompts, max_new_tokens=8)
        np.testing.assert_array_equal(spec.tokens, ref.tokens)

    def test_eos_stops_generation(self, target_params):
        # pick the token the model actually emits first as the EOS, so
        # generation must stop at length 1
        prompts = [[2, 3, 4]]
        ref = vanilla(target_params, prompts, 6)
        eos = int(ref.tokens[0, 0])
        spec = SpeculativeEngine(
            target_params, TINY, target_params, TINY, k=3
        ).generate(prompts, max_new_tokens=6, eos_id=eos)
        assert spec.lengths[0] == 1
        assert spec.tokens[0, 0] == eos
        # padding after EOS is eos_id (engine contract)
        assert (spec.tokens[0, 1:] == eos).all()

    def test_eos_mid_stream_matches_vanilla(self, target_params):
        prompts = [[11, 12, 13, 14]]
        ref = vanilla(target_params, prompts, 10)
        # choose an EOS that appears mid-stream in the vanilla output
        # (fall back to the 3rd token)
        eos = int(ref.tokens[0, 2])
        ref_eos = vanilla(target_params, prompts, 10, eos_id=eos)
        spec = SpeculativeEngine(
            target_params, TINY, target_params, TINY, k=4
        ).generate(prompts, max_new_tokens=10, eos_id=eos)
        np.testing.assert_array_equal(spec.tokens, ref_eos.tokens)
        np.testing.assert_array_equal(spec.lengths, ref_eos.lengths)

    def test_max_new_one(self, target_params, draft_params):
        prompts = [[1, 2]]
        ref = vanilla(target_params, prompts, 1)
        spec = SpeculativeEngine(
            target_params, TINY, draft_params, DRAFT_CFG, k=2
        ).generate(prompts, max_new_tokens=1)
        np.testing.assert_array_equal(spec.tokens, ref.tokens)

    def test_vocab_mismatch_rejected(self, target_params, draft_params):
        bad = ModelConfig(
            vocab_size=TINY.vocab_size * 2,
            hidden_size=32,
            intermediate_size=64,
            num_hidden_layers=1,
            num_attention_heads=2,
            num_key_value_heads=2,
        )
        with pytest.raises(ValueError, match="vocabulary"):
            SpeculativeEngine(
                target_params, TINY, init_params(bad, jax.random.PRNGKey(1)),
                bad,
            )


class TestAcceptanceDiagnostics:
    def test_self_draft_sustained_acceptance(self, target_params):
        # draft == target: every proposal accepted, so 20 post-first
        # tokens need ceil(20/(k+1)) = 4 rounds. The r2 draft-cache-hole
        # bug (bonus token's predecessor never processed by the draft)
        # collapsed acceptance after the first full round, blowing this
        # up to ~20 rounds while leaving outputs identical.
        k = 4
        eng = SpeculativeEngine(target_params, TINY, target_params, TINY, k=k)
        out = eng.generate([[3, 1, 4, 1, 5]], max_new_tokens=21)
        assert out.lengths[0] == 21
        assert eng.last_stats["rounds"] <= 5  # ceil(20/5) + 1 slack
        assert eng.last_stats["accepted_drafts"][0] >= 21 - 1 - eng.last_stats["rounds"]

    def test_depth_below_one_rejected(self, target_params):
        with pytest.raises(ValueError, match="k must be >= 1"):
            SpeculativeEngine(target_params, TINY, target_params, TINY, k=0)

    def test_fits_accounts_for_slack(self, target_params):
        eng = SpeculativeEngine(
            target_params, TINY, target_params, TINY, k=4, max_cache_len=64
        )
        assert eng.fits(32, 27)       # 32+27+5 = 64
        assert not eng.fits(32, 28)   # 65 > 64


class TestSampledSpeculative:
    """Rejection-sampling correction: sampled speculative output must be
    distributed EXACTLY as vanilla sampling from the target — for any
    draft. Verified empirically on a 16-token vocabulary (large enough
    batches that total-variation noise is well under the threshold) for
    both a self-draft (acceptance ~1: bonus-token path) and an
    independent random draft (low acceptance: residual-resample path)."""

    VOCAB16 = ModelConfig(
        vocab_size=16,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=2,
        num_key_value_heads=2,
        max_position_embeddings=128,
    )

    def _pooled_dist(self, gen_fn, n_batches=3, B=256, max_new=3):
        counts = np.zeros(16, np.int64)
        prompt = [3, 7, 1, 9]
        for seed in range(n_batches):
            out = gen_fn([prompt] * B, max_new, seed)
            for b in range(B):
                for t in out.tokens[b, : out.lengths[b]]:
                    counts[int(t)] += 1
        return counts / counts.sum()

    def _tv(self, a, b):
        return 0.5 * float(np.abs(a - b).sum())

    @pytest.mark.parametrize("self_draft", [True, False])
    def test_sampled_matches_vanilla_distribution(self, self_draft):
        cfg = self.VOCAB16
        tparams = init_params(cfg, jax.random.PRNGKey(0))
        dparams = (
            tparams if self_draft else init_params(cfg, jax.random.PRNGKey(9))
        )
        spec = SpeculativeEngine(tparams, cfg, dparams, cfg, k=3)
        eng = Engine(tparams, cfg)

        temperature, top_p = 0.8, 0.9

        spec_dist = self._pooled_dist(
            lambda p, m, s: spec.generate(
                p, max_new_tokens=m, temperature=temperature, top_p=top_p,
                seed=s,
            )
        )
        van_dist = self._pooled_dist(
            lambda p, m, s: eng.generate(
                p, max_new_tokens=m, temperature=temperature, top_p=top_p,
                seed=s,
            )
        )
        tv = self._tv(spec_dist, van_dist)
        assert tv < 0.12, f"TV(spec, vanilla) = {tv:.3f} (self={self_draft})"
        # sensitivity: a genuinely different distribution (greedy
        # collapse) is far away — the threshold above is discriminative
        greedy_dist = self._pooled_dist(
            lambda p, m, s: eng.generate(p, max_new_tokens=m, seed=s),
            n_batches=1, B=64,
        )
        assert self._tv(van_dist, greedy_dist) > 0.3

    def test_sampled_seed_deterministic(self, target_params, draft_params):
        spec = SpeculativeEngine(target_params, TINY, draft_params, DRAFT_CFG)
        prompts = [[5, 6, 7]]
        a = spec.generate(prompts, max_new_tokens=6, temperature=0.7,
                          top_p=0.9, seed=11)
        b = spec.generate(prompts, max_new_tokens=6, temperature=0.7,
                          top_p=0.9, seed=11)
        np.testing.assert_array_equal(a.tokens, b.tokens)
        c = spec.generate(prompts, max_new_tokens=6, temperature=0.7,
                          top_p=0.9, seed=12)
        assert not np.array_equal(a.tokens, c.tokens) or a.lengths[0] <= 1

    def test_sampled_acceptance_nonzero_with_self_draft(self, target_params):
        # p == q: every draft token accepted (u*q < p a.s.), so the
        # speedup survives sampling
        spec = SpeculativeEngine(target_params, TINY, target_params, TINY,
                                 k=3)
        out = spec.generate([[2, 3, 4]], max_new_tokens=12, temperature=0.9,
                            seed=0)
        assert out.lengths[0] == 12
        assert spec.last_stats["accepted_drafts"].sum() >= 6


class TestIncrementalGroups:
    """The incremental group API (start_group/step_group/finish_group)
    must be BIT-identical to the bulk generate() — both run the shared
    _prefill_state + _one_round trace, split only at the jit boundary.
    The batcher relies on this: a request served through an interleaved
    group must emit exactly what a solo draft call would have."""

    def _run_incremental(self, spec, prompts, max_new, **kw):
        g = spec.start_group(prompts, max_new_tokens=max_new, **kw)
        rounds = 0
        while not spec.step_group(g):
            rounds += 1
            assert rounds <= max_new + 2, "group never converged"
        return spec.finish_group(g)

    def test_matches_bulk_greedy(self, target_params, draft_params):
        spec = SpeculativeEngine(target_params, TINY, draft_params,
                                 DRAFT_CFG, k=3)
        prompts = [[5, 6, 7], [2, 3], [9, 1, 4, 8]]
        bulk = spec.generate(prompts, max_new_tokens=6)
        inc = self._run_incremental(spec, prompts, 6)
        np.testing.assert_array_equal(inc.tokens, bulk.tokens)
        np.testing.assert_array_equal(inc.lengths, bulk.lengths)

    def test_matches_bulk_sampled(self, target_params, draft_params):
        spec = SpeculativeEngine(target_params, TINY, draft_params,
                                 DRAFT_CFG, k=2)
        prompts = [[5, 6, 7], [8, 1]]
        bulk = spec.generate(prompts, max_new_tokens=5, temperature=0.8,
                             top_p=0.9, seed=13)
        inc = self._run_incremental(
            spec, prompts, 5, temperatures=[0.8, 0.8],
            top_ps=[0.9, 0.9], seed=13,
        )
        np.testing.assert_array_equal(inc.tokens, bulk.tokens)
        np.testing.assert_array_equal(inc.lengths, bulk.lengths)

    def test_eos_stops_incremental_early(self, target_params):
        spec = SpeculativeEngine(target_params, TINY, target_params, TINY,
                                 k=3)
        free = spec.generate([[5, 17, 42]], max_new_tokens=8)
        eos = int(free.tokens[0, 1])
        bulk = spec.generate([[5, 17, 42]], max_new_tokens=8, eos_id=eos)
        inc = self._run_incremental(spec, [[5, 17, 42]], 8, eos_id=eos)
        np.testing.assert_array_equal(inc.tokens, bulk.tokens)
        np.testing.assert_array_equal(inc.lengths, bulk.lengths)

    def test_per_row_warp_marginals(self):
        """A heterogeneous sampled group (two temperature populations in
        one draft batch) must give EACH row the same marginal
        distribution as vanilla sampling at that row's temperature —
        the per-row warp + per-row rejection correction contract."""
        cfg = TestSampledSpeculative.VOCAB16
        tparams = init_params(cfg, jax.random.PRNGKey(0))
        dparams = init_params(cfg, jax.random.PRNGKey(9))
        spec = SpeculativeEngine(tparams, cfg, dparams, cfg, k=3)
        eng = Engine(tparams, cfg)

        B, half, max_new = 256, 128, 3
        prompt = [3, 7, 1, 9]
        temps = [0.7] * half + [1.4] * half
        counts = np.zeros((2, 16), np.int64)
        for seed in range(3):
            g = spec.start_group(
                [prompt] * B, max_new_tokens=max_new,
                temperatures=temps, seed=seed,
            )
            while not spec.step_group(g):
                pass
            out = spec.finish_group(g)
            for b in range(B):
                for t in out.tokens[b, : out.lengths[b]]:
                    counts[b // half, int(t)] += 1
        got = counts / counts.sum(axis=1, keepdims=True)

        for pop, temp in ((0, 0.7), (1, 1.4)):
            van = np.zeros(16, np.int64)
            for seed in range(3):
                out = eng.generate([prompt] * B, max_new_tokens=max_new,
                                   temperature=temp, seed=seed + 100)
                for b in range(B):
                    for t in out.tokens[b, : out.lengths[b]]:
                        van[int(t)] += 1
            van = van / van.sum()
            tv = 0.5 * float(np.abs(got[pop] - van).sum())
            assert tv < 0.12, f"temp={temp}: TV={tv:.3f}"
        # discriminative: the two populations differ from each other
        assert 0.5 * float(np.abs(got[0] - got[1]).sum()) > 0.05
