"""Sharded solver on the virtual 8-device CPU mesh.

Same assertions as the single-device solver tests: the sharded path must
produce valid assignments (capacity, padding, gang invariants) and place
everything placeable — sharding is a placement concern, not a semantics
change."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from kubeinfer_tpu.solver import ScoreWeights, solve_greedy
from kubeinfer_tpu.solver.problem import encode_problem_arrays
from kubeinfer_tpu.solver.sharded import make_mesh, shard_problem, solve_sharded

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual CPU mesh"
)


def random_problem(J=500, N=64, seed=0):
    rng = np.random.default_rng(seed)
    return encode_problem_arrays(
        job_gpu=rng.integers(1, 4, J).astype(np.float32),
        job_mem_gib=rng.integers(1, 16, J).astype(np.float32),
        job_priority=rng.integers(0, 4, J).astype(np.float32),
        node_gpu_free=np.full(N, 32.0, np.float32),
        node_mem_free_gib=np.full(N, 256.0, np.float32),
    )


def check_assignment(p, a, J, N):
    node = np.asarray(a.node)
    assert node.shape[0] >= J
    assert (node[J:] == -1).all(), "padding jobs placed"
    placed = node[:J]
    gpu = np.asarray(p.jobs.gpu_demand)[:J]
    mem = np.asarray(p.jobs.mem_demand)[:J]
    used_g = np.zeros(N)
    used_m = np.zeros(N)
    for j, n in enumerate(placed):
        if n >= 0:
            assert n < N, "placed on padding node"
            used_g[n] += gpu[j]
            used_m[n] += mem[j]
    assert (used_g <= np.asarray(p.nodes.gpu_free)[:N] + 1e-3).all()
    assert (used_m <= np.asarray(p.nodes.mem_free)[:N] + 1e-3).all()


class TestMesh:
    def test_make_mesh_shapes(self):
        m = make_mesh(8)
        assert m.devices.shape == (8, 1)
        m2 = make_mesh(8, job_axis=4, node_axis=2)
        assert m2.devices.shape == (4, 2)
        with pytest.raises(ValueError):
            make_mesh(8, job_axis=3, node_axis=2)

    def test_shard_problem_places_axes(self):
        p = random_problem()
        mesh = make_mesh(8)
        sp = shard_problem(p, mesh)
        # job axis split 8 ways; node axis replicated (axis size 1)
        assert sp.jobs.gpu_demand.sharding.spec == jax.sharding.PartitionSpec("jobs")
        shard_shapes = {s.data.shape for s in sp.jobs.gpu_demand.addressable_shards}
        assert shard_shapes == {(sp.jobs.gpu_demand.shape[0] // 8,)}


class TestShardedSolve:
    def test_data_parallel_solve_valid_and_complete(self):
        p = random_problem(J=500, N=64)
        out = solve_sharded(p, make_mesh(8))
        check_assignment(p, out, 500, 64)
        assert int(out.placed) == 500  # ample capacity: all placed

    def test_2d_mesh_solve(self):
        p = random_problem(J=300, N=64, seed=3)
        out = solve_sharded(p, make_mesh(8, job_axis=4, node_axis=2))
        check_assignment(p, out, 300, 64)
        assert int(out.placed) == 300

    def test_matches_single_device_placement_count(self):
        # Oversubscribed: placement counts must agree with the single-device
        # solve (same deterministic algorithm, different partitioning).
        rng = np.random.default_rng(7)
        J, N = 400, 16
        p = encode_problem_arrays(
            job_gpu=rng.integers(1, 8, J).astype(np.float32),
            job_mem_gib=rng.integers(1, 8, J).astype(np.float32),
            node_gpu_free=np.full(N, 16.0, np.float32),
            node_mem_free_gib=np.full(N, 64.0, np.float32),
        )
        single = solve_greedy(p)
        sharded = solve_sharded(p, make_mesh(8))
        assert int(sharded.placed) == int(single.placed)

    def test_gang_and_priority_preserved_under_sharding(self):
        J = 200
        gang = np.full(J, -1, np.int32)
        gang[:8] = 5  # one infeasible gang (8 x 8 chips > any node)
        p = encode_problem_arrays(
            job_gpu=np.concatenate(
                [np.full(8, 8.0), np.ones(J - 8)]
            ).astype(np.float32),
            job_mem_gib=np.ones(J, np.float32),
            job_gang=gang,
            job_priority=np.concatenate(
                [np.zeros(8), np.full(J - 8, 5.0)]
            ).astype(np.float32),
            node_gpu_free=np.full(4, 8.0, np.float32),
            node_mem_free_gib=np.full(4, 64.0, np.float32),
        )
        out = solve_sharded(p, make_mesh(8))
        node = np.asarray(out.node)
        assert (node[:8] == -1).all()  # gang unwound atomically
        assert int(out.placed) == 32  # 4 nodes x 8 single-chip jobs
