"""Checkpoint round-trip tests (orbax), including sharded restore."""

from __future__ import annotations

import jax
import numpy as np
import pytest

pytest.importorskip("orbax.checkpoint")

from kubeinfer_tpu.inference import PRESETS, init_params  # noqa: E402
from kubeinfer_tpu.inference.checkpoint import (
    restore_checkpoint,
    save_checkpoint,
)
from kubeinfer_tpu.inference.sharding import make_inference_mesh

TINY = PRESETS["tiny"]


def assert_trees_equal(a, b):
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y)
        ),
        a, b,
    )


def test_roundtrip(tmp_path):
    params = init_params(TINY, jax.random.PRNGKey(0))
    save_checkpoint(str(tmp_path / "ckpt"), params, TINY, step=17)
    restored, cfg, step = restore_checkpoint(str(tmp_path / "ckpt"))
    assert step == 17
    assert cfg == TINY
    assert_trees_equal(params, restored)


def test_sharded_restore_lands_on_mesh(tmp_path):
    params = init_params(TINY, jax.random.PRNGKey(1))
    save_checkpoint(str(tmp_path / "ckpt"), params, TINY, step=3)
    mesh = make_inference_mesh(tp=2, sp=1, dp=4)
    restored, cfg, step = restore_checkpoint(str(tmp_path / "ckpt"), mesh=mesh)
    assert step == 3
    assert_trees_equal(params, restored)
    # TP placement applied: q_proj shards over the tp axis
    sh = restored["layers"][0]["q_proj"].sharding
    assert sh.spec == jax.sharding.PartitionSpec(None, "tp")


def test_resume_training_continues(tmp_path):
    from kubeinfer_tpu.inference.train import train_step

    rng = np.random.default_rng(0)
    toks = np.asarray(rng.integers(0, TINY.vocab_size, (2, 12)), np.int32)
    params = init_params(TINY, jax.random.PRNGKey(2))
    params, _ = train_step(params, toks, TINY)
    save_checkpoint(str(tmp_path / "ckpt"), params, TINY, step=1)
    restored, _, step = restore_checkpoint(str(tmp_path / "ckpt"))
    _, loss_a = train_step(restored, toks, TINY)
    params_b = init_params(TINY, jax.random.PRNGKey(2))
    params_b, _ = train_step(params_b, toks, TINY)
    _, loss_b = train_step(params_b, toks, TINY)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-6)


def test_restore_missing_path_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        restore_checkpoint(str(tmp_path / "nope"))


@pytest.mark.parametrize("family_kw", [
    {"qkv_bias": True},
    {"num_local_experts": 4, "num_experts_per_tok": 2},
])
def test_roundtrip_qwen2_and_mixtral_trees(tmp_path, family_kw):
    # family-specific param subtrees (biases / the nested moe dict) must
    # survive the save/restore template derivation
    from kubeinfer_tpu.inference import ModelConfig

    cfg = ModelConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, **family_kw,
    )
    params = init_params(cfg, jax.random.PRNGKey(3))
    save_checkpoint(str(tmp_path / "ck"), params, cfg, step=7)
    restored, rcfg, step = restore_checkpoint(str(tmp_path / "ck"))
    assert step == 7
    assert rcfg == cfg
    assert_trees_equal(params, restored)
