"""Manager composition tests: endpoints, auth, readiness, HA failover.

Parity targets: reference cmd/manager/main.go — health/ready probes
(:190-197), secured metrics (:126-138), leader election (:162-163).
"""

from __future__ import annotations

import time
import urllib.error
import urllib.request

import pytest

from kubeinfer_tpu.api.types import LLMService
from kubeinfer_tpu.controlplane.httpstore import RemoteStore, StoreServer
from kubeinfer_tpu.controlplane.store import Store
from kubeinfer_tpu.manager import Manager, ManagerConfig


def http_get(url: str, token: str = "") -> tuple[int, str]:
    req = urllib.request.Request(url)
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(req, timeout=5) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, ""


def ephemeral_config(**over) -> ManagerConfig:
    cfg = ManagerConfig(
        store_bind_port=0, metrics_bind_port=0, health_bind_port=0,
        tick_interval_s=0.1,
    )
    for k, v in over.items():
        setattr(cfg, k, v)
    return cfg


def wait_until(pred, timeout_s: float, what: str):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    pytest.fail(f"timed out waiting for {what}")


def sample_svc(name: str = "svc") -> dict:
    svc = LLMService.from_dict(
        {"metadata": {"name": name}, "spec": {"model": "org/m", "replicas": 1}}
    )
    return svc.to_dict()


class TestManagerEndpoints:
    def test_probes_metrics_and_reconcile(self):
        mgr = Manager(ephemeral_config(auth_token="tok")).start()
        try:
            health = f"http://127.0.0.1:{mgr.health_server.port}"
            metrics_url = f"http://127.0.0.1:{mgr.metrics_server.port}/metrics"

            assert http_get(f"{health}/healthz")[0] == 200
            wait_until(
                lambda: http_get(f"{health}/readyz")[0] == 200, 10, "readyz"
            )

            # secured metrics: 401 anonymous, 200 with token, probe open
            assert http_get(metrics_url)[0] == 401
            code, body = http_get(metrics_url, token="tok")
            assert code == 200 and "kubeinfer_reconcile_total" in body
            mport = mgr.metrics_server.port
            assert http_get(f"http://127.0.0.1:{mport}/healthz")[0] == 200

            # the hosted store reconciles CRs applied over the wire
            remote = RemoteStore(mgr.store_address, token="tok")
            remote.create(LLMService.KIND, sample_svc())
            wait_until(
                lambda: remote.get(LLMService.KIND, "svc")["status"]["phase"]
                in ("Pending", "Scheduling"),
                10, "status synced by controller",
            )
            # no nodes exist → replicas stay unplaced, phase Pending
            assert (
                remote.get(LLMService.KIND, "svc")["status"]["phase"] == "Pending"
            )
        finally:
            mgr.stop()


class TestManagerHA:
    def test_leader_election_failover(self):
        # External store (the HA topology: managers share one control
        # plane, exactly how reference managers share one API server).
        backing = Store()
        store_srv = StoreServer(backing, port=0).start()
        try:
            timings = (1.0, 0.5, 0.1)
            mk = lambda ident: Manager(ephemeral_config(
                store_connect=store_srv.address, leader_elect=True,
                identity=ident, lease_timings=timings,
            ))
            a = mk("manager-a").start()
            wait_until(lambda: a._is_leader.is_set(), 10, "A leads")

            b = mk("manager-b").start()
            time.sleep(0.5)
            assert not b._is_leader.is_set(), "standby must not lead"

            # A's clean stop surrenders the lease; B takes over
            a.stop()
            wait_until(lambda: b._is_leader.is_set(), 10, "B takeover")

            # B now reconciles: applied CRs get status
            remote = RemoteStore(store_srv.address)
            remote.create(LLMService.KIND, sample_svc("ha-svc"))
            wait_until(
                lambda: remote.get(LLMService.KIND, "ha-svc")["status"][
                    "phase"] == "Pending",
                10, "B reconciles",
            )
            b.stop()
        finally:
            store_srv.shutdown()
