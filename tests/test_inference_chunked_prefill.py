"""Chunked prefill + SLO-aware preemption contracts.

Two invariants carry this scheduler feature:

- **Token identity.** Splitting a prefill into chunk dispatches, or
  parking a decoding row and readmitting it later, must not change a
  single emitted token — greedy AND sampled. The sampling key schedule
  is position-folded (admit folds the effective prompt length, decode
  folds offset+1), so a resumed row draws exactly the noise the
  uninterrupted run would have drawn; these tests pin that end to end
  against uncontended runs of the same engine class.

- **Static shapes.** Chunk dispatches are one compiled shape (k full
  blocks) and final suffixes ride the canonical prompt buckets, so the
  chunk path must add at most {chunk} ∪ existing buckets to the
  compile-shape set — asserted through the StepProfiler's first-seen
  compile counter, which would grow on any ad-hoc shape.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from kubeinfer_tpu.inference import PRESETS, init_params
from kubeinfer_tpu.inference.batching import (
    ContinuousEngine,
    PreemptionPolicy,
)
from kubeinfer_tpu.inference.engine import PROMPT_BUCKETS

TINY = PRESETS["tiny"]

# aggressive enough that a 2-slot engine under an 8-deep backlog parks
# rows within a few decode steps; min_progress/cooldown stay nonzero so
# the anti-livelock levers are exercised, not bypassed
AGGRESSIVE = PreemptionPolicy(
    threshold_s=0.0005, objective=0.5, burn_limit=0.5,
    cooldown_steps=1, min_progress=1,
)


@pytest.fixture(scope="module")
def params():
    return init_params(TINY, jax.random.PRNGKey(6))


def _engine(params, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("cache_len", 64)
    kw.setdefault("block_size", 8)
    return ContinuousEngine(params, TINY, **kw).start()


class TestChunkedPrefill:
    def test_single_chunk_identity_and_telemetry(self, params):
        # 25-token prompt, chunk = 2 blocks * 8 = 16: one intermediate
        # chunk dispatch + a 16-bucket final suffix — the smallest
        # workload that exercises the chunk path at all
        rng = np.random.default_rng(2)
        prompt = rng.integers(0, TINY.vocab_size, 25).tolist()
        plain = _engine(params, cache_len=128)
        try:
            want = plain.generate(prompt, max_new_tokens=6)
        finally:
            plain.stop()
        eng = _engine(params, cache_len=128, prefill_chunk_blocks=2)
        try:
            got = eng.generate(prompt, max_new_tokens=6)
            chunks = eng.chunks_total
            recs = eng.profiler.snapshot()
            kinds = {e.kind for e in eng.flight.snapshot()}
        finally:
            eng.stop()
        assert got == want
        assert chunks == 1
        chunk_recs = [r for r in recs if r.phase == "chunk"]
        assert len(chunk_recs) == 1
        # every chunk token is live prompt work — no bucket padding
        assert chunk_recs[0].bucket == 16
        assert chunk_recs[0].live_tokens == 16
        assert chunk_recs[0].padded_tokens == 0
        assert "chunk" in kinds

    @pytest.mark.slow
    def test_multi_chunk_parity_greedy_sampled_and_shapes(self, params):
        """Compile-heaviest parity sweep: multi-chunk prompts, greedy
        and sampled, chunked vs unchunked engines of the same class
        (the per-request Engine has a different key schedule, so the
        sampled reference must be an uncontended ContinuousEngine)."""
        rng = np.random.default_rng(3)
        long_p = rng.integers(0, TINY.vocab_size, 49).tolist()
        mid_p = rng.integers(0, TINY.vocab_size, 37).tolist()
        kw = dict(cache_len=128)
        plain = _engine(params, **kw)
        try:
            want = [
                plain.generate(long_p, max_new_tokens=8),
                plain.generate(mid_p, max_new_tokens=8),
                plain.generate(long_p, max_new_tokens=8,
                               temperature=0.8, seed=7, top_k=9),
            ]
        finally:
            plain.stop()
        eng = _engine(params, prefill_chunk_blocks=2, **kw)
        try:
            got = [
                eng.generate(long_p, max_new_tokens=8),
                eng.generate(mid_p, max_new_tokens=8),
                eng.generate(long_p, max_new_tokens=8,
                             temperature=0.8, seed=7, top_k=9),
            ]
            assert eng.chunks_total >= 4  # 3 for len-49, 1+ for len-37
            recs = eng.profiler.snapshot()
            # shape discipline: chunks are the ONE configured shape,
            # suffixes are canonical buckets — nothing ad hoc
            assert all(
                r.bucket == 16 for r in recs if r.phase == "chunk"
            )
            assert all(
                r.bucket in PROMPT_BUCKETS
                for r in recs if r.phase == "prefill"
            )
            # the compile counter must stay FLAT on a repeat of an
            # already-seen length: any data-dependent shape would
            # register as a fresh (phase, bucket) first-seen here
            c0 = eng.profiler.compile_count
            got.append(
                eng.generate(
                    rng.integers(0, TINY.vocab_size, 49).tolist(),
                    max_new_tokens=8,
                )
            )
            assert eng.profiler.compile_count == c0
        finally:
            eng.stop()
        assert got[:3] == want
        assert len(got[3]) == 8


class TestPreemption:
    def test_parse(self):
        pol = PreemptionPolicy.parse("0.25")
        assert pol.threshold_s == 0.25 and pol.burn_limit == 1.0
        pol = PreemptionPolicy.parse("0.25:2.0")
        assert pol.burn_limit == 2.0
        with pytest.raises(ValueError, match="THRESHOLD_S"):
            PreemptionPolicy.parse("0.25:2.0:9")

    def test_preempt_resume_token_identity(self, params):
        """The pinned warm-resume contract: under sustained preemption
        every request's output — greedy and sampled — is identical to
        an uncontended run of the same engine class."""
        prompts = [
            [i + 1, i + 2, i + 3, i + 4, i + 5] for i in range(8)
        ]
        samp = dict(temperature=0.9, top_k=11)
        solo = _engine(params)
        try:
            ref_g = [solo.generate(p, max_new_tokens=10) for p in prompts]
            ref_s = [
                solo.generate(p, max_new_tokens=10, seed=100 + i, **samp)
                for i, p in enumerate(prompts)
            ]
        finally:
            solo.stop()
        eng = _engine(params, preemption=AGGRESSIVE)
        try:
            reqs_g = [
                eng.submit(p, max_new_tokens=10) for p in prompts
            ]
            reqs_s = [
                eng.submit(p, max_new_tokens=10, seed=100 + i, **samp)
                for i, p in enumerate(prompts)
            ]
            for r in reqs_g + reqs_s:
                assert r.done.wait(300)
                assert not r.failed
            preempted = eng.preempted_total
            resumed = eng.resumed_total
            kinds = {e.kind for e in eng.flight.snapshot()}
        finally:
            eng.stop()
        # the scenario must actually exercise the mechanism — a policy
        # change that stops preemption from firing would otherwise turn
        # the identity asserts below into a vacuous pass
        assert preempted > 0
        assert resumed == preempted  # every parked row readmitted
        assert {"preempt", "resume"} <= kinds
        for i, r in enumerate(reqs_g):
            assert r.out_tokens == ref_g[i], f"greedy {i}"
        for i, r in enumerate(reqs_s):
            assert r.out_tokens == ref_s[i], f"sampled {i}"

    def test_oversubscribed_no_livelock(self, params):
        """Anti-livelock: 12 requests through 2 slots with preemption
        firing at every opportunity must still retire EVERY request with
        its full budget — longest-pending-first admission plus the
        min_progress/cooldown gates guarantee forward progress (a
        thrashing scheduler would park rows before they decode and spin
        the same pair forever)."""
        rng = np.random.default_rng(9)
        reqs = []
        eng = _engine(params, preemption=AGGRESSIVE)
        try:
            for i in range(12):
                p = rng.integers(0, TINY.vocab_size, 6).tolist()
                reqs.append(eng.submit(
                    p, max_new_tokens=8,
                    temperature=0.7 if i % 2 else 0.0, seed=i,
                ))
            for i, r in enumerate(reqs):
                assert r.done.wait(300), f"request {i} starved"
                assert not r.failed
                assert len(r.out_tokens) == 8, f"request {i} truncated"
            assert eng.preempted_total > 0
            stats = eng.scheduler_stats()
        finally:
            eng.stop()
        # quiescent engine: nothing parked, nothing mid-prefill
        assert stats["parked"] == 0
        assert stats["chunk_queue"] == 0

    def test_scheduler_metrics_exposure(self, params):
        from kubeinfer_tpu.inference.engine import Engine
        from kubeinfer_tpu.inference.server import InferenceServer

        rng = np.random.default_rng(10)
        eng = _engine(
            params, cache_len=128, prefill_chunk_blocks=2,
            preemption=AGGRESSIVE,
        )
        srv = InferenceServer(
            Engine(params, TINY), model_id="tiny", port=0,
            continuous=eng,
        )
        try:
            reqs = [
                eng.submit(
                    rng.integers(0, TINY.vocab_size, 25).tolist(),
                    max_new_tokens=8,
                )
                for _ in range(6)
            ]
            for r in reqs:
                assert r.done.wait(300)
                assert not r.failed
            srv._refresh_spec_metrics()
            # delta-at-scrape counters: a second refresh with no new
            # engine activity must not double-count
            srv._refresh_spec_metrics()
            out = srv.registry.render()
            totals = eng.scheduler_stats()
        finally:
            eng.stop()
        lines = dict(
            ln.rsplit(" ", 1)
            for ln in out.splitlines()
            if ln and not ln.startswith("#")
        )
        assert int(lines["kubeinfer_prefill_chunks_total"]) == \
            totals["chunks"] > 0
        assert int(lines["kubeinfer_preemptions_total"]) == \
            totals["preempted"]
        assert int(lines["kubeinfer_preemption_resumes_total"]) == \
            totals["resumed"]
        assert int(lines["kubeinfer_prefill_chunk_queue_depth"]) == 0
        assert int(lines["kubeinfer_parked_requests"]) == 0
