"""Hardware probe tests (runs on the virtual CPU mesh)."""

from __future__ import annotations

from kubeinfer_tpu.agent.probe import probe_accelerators, probe_host_memory


def test_probe_sees_local_devices():
    info = probe_accelerators()
    assert info is not None
    # conftest forces an 8-device virtual CPU mesh
    assert info.count == 8
    assert info.platform == "cpu"


def test_probe_host_memory_on_linux():
    mem = probe_host_memory()
    assert mem is not None
    total, avail = mem
    assert total > 0 and 0 < avail <= total
