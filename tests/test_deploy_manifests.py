"""Deploy-manifest validation tier (r2 verdict missing #2).

The reference's e2e deploys its manifests to a real cluster
(test/e2e/e2e_test.go:48-337); Kind isn't available in this environment,
so this tier pins the same intent statically: every YAML under deploy/
parses, the env contract the manifests inject matches what the agent
actually reads, manager args/ports match the real CLI and ManagerConfig,
and the sample CRs pass admission validation. A drifted env var name,
flag, or port fails `make test` (and CI).
"""

from __future__ import annotations

import os
import re

import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEPLOY = os.path.join(REPO, "deploy")


def _load_all(path):
    with open(path) as f:
        return [d for d in yaml.safe_load_all(f) if d]


def _all_manifest_paths():
    out = []
    for root, _, files in os.walk(DEPLOY):
        for name in files:
            if name.endswith((".yaml", ".yml")):
                out.append(os.path.join(root, name))
    return sorted(out)


def _agent_env_contract() -> set[str]:
    """Env names the agent binary actually reads, scraped from its
    source — the single source of truth the manifests must match."""
    src = open(
        os.path.join(REPO, "kubeinfer_tpu", "agent", "__main__.py")
    ).read()
    names = set(re.findall(r'os\.environ(?:\.get)?\(\s*"([A-Z0-9_]+)"', src))
    names |= set(re.findall(r'"([A-Z0-9_]+)" (?:not )?in os\.environ', src))
    return names


def _containers(doc):
    spec = doc.get("spec", {})
    tmpl = spec.get("template", {}).get("spec", {})
    return tmpl.get("containers", [])


class TestParse:
    @pytest.mark.parametrize("path", _all_manifest_paths())
    def test_yaml_parses(self, path):
        docs = _load_all(path)
        assert docs, f"{path} contains no documents"


class TestAgentEnvContract:
    def test_daemonset_env_names_are_read_by_the_agent(self):
        contract = _agent_env_contract()
        assert "STORE_ADDR" in contract  # scrape sanity
        docs = _load_all(os.path.join(DEPLOY, "kubernetes", "agent.yaml"))
        ds = next(d for d in docs if d["kind"] == "DaemonSet")
        env_names = {
            e["name"] for c in _containers(ds) for e in c.get("env", [])
        }
        unknown = env_names - contract
        assert not unknown, (
            f"agent.yaml injects env vars the agent never reads: {unknown} "
            f"(agent contract: {sorted(contract)})"
        )
        # the required minimum to join the control plane
        assert {"NODE_NAME", "STORE_ADDR"} <= env_names

    def test_compose_agent_env_names_are_read_by_the_agent(self):
        contract = _agent_env_contract()
        compose = _load_all(
            os.path.join(DEPLOY, "docker-compose.yaml")
        )[0]
        for name, svc in compose["services"].items():
            cmd = svc.get("command")
            is_agent = "entrypoint" not in svc and name != "manager" and (
                not isinstance(cmd, list) or "kubeinfer_tpu.manager"
                not in " ".join(map(str, cmd))
            )
            if not is_agent:
                continue
            env = svc.get("environment", {})
            names = set(env if isinstance(env, dict)
                        else [e.split("=", 1)[0] for e in env])
            unknown = names - contract
            assert not unknown, (
                f"compose service {name!r} sets env the agent never "
                f"reads: {unknown}"
            )


class TestManagerArgsAndPorts:
    def _manager_args(self, doc):
        for c in _containers(doc):
            if "manager" in c.get("name", ""):
                return c.get("args", []) or c.get("command", [])
        return []

    def test_kubernetes_manager_args_parse_against_the_real_cli(self):
        from kubeinfer_tpu.manager.__main__ import build_parser

        docs = _load_all(os.path.join(DEPLOY, "kubernetes", "manager.yaml"))
        dep = next(d for d in docs if d["kind"] == "Deployment")
        args = self._manager_args(dep)
        assert args
        build_parser().parse_args(args)  # SystemExit on any drifted flag

    def test_compose_manager_args_parse_against_the_real_cli(self):
        from kubeinfer_tpu.manager.__main__ import build_parser

        compose = _load_all(os.path.join(DEPLOY, "docker-compose.yaml"))[0]
        mgr = compose["services"]["manager"]
        args = [a for a in mgr.get("command", []) if a.startswith("--")]
        assert args
        build_parser().parse_args(args)

    def test_container_ports_match_bind_addresses(self):
        docs = _load_all(os.path.join(DEPLOY, "kubernetes", "manager.yaml"))
        dep = next(d for d in docs if d["kind"] == "Deployment")
        args = self._manager_args(dep)
        bound = {
            int(a.rsplit(":", 1)[1])
            for a in args
            if "-bind-address" in a or "-address" in a and ":" in a
        }
        container = next(
            c for c in _containers(dep) if "manager" in c["name"]
        )
        declared = {p["containerPort"] for p in container.get("ports", [])}
        assert declared <= bound, (
            f"manager.yaml declares ports {declared - bound} that no "
            f"--*-bind-address flag binds (bound: {bound})"
        )

    def test_service_ports_are_container_ports(self):
        docs = _load_all(os.path.join(DEPLOY, "kubernetes", "manager.yaml"))
        dep = next(d for d in docs if d["kind"] == "Deployment")
        svc = next(d for d in docs if d["kind"] == "Service")
        container_ports = {
            p["containerPort"]
            for c in _containers(dep)
            for p in c.get("ports", [])
        }
        for p in svc["spec"]["ports"]:
            assert p["port"] in container_ports, (
                f"Service exposes {p['port']} which no manager container "
                f"declares ({container_ports})"
            )

    def test_default_ports_match_manager_config(self):
        """The documented default ports and the ManagerConfig defaults
        must agree — manifests pin 1808x explicitly, and a silent default
        change would strand every README/quickstart example."""
        from kubeinfer_tpu.manager import ManagerConfig

        cfg = ManagerConfig()
        assert cfg.store_bind_port == 18080
        assert cfg.metrics_bind_port == 18081
        assert cfg.health_bind_port == 18082


class TestMonitorAndNetworkPolicy:
    def test_servicemonitor_selects_the_manager_service(self):
        docs = _load_all(os.path.join(DEPLOY, "kubernetes", "monitor.yaml"))
        mon = next(d for d in docs if d["kind"] == "ServiceMonitor")
        sel = mon["spec"]["selector"]["matchLabels"]
        svc_docs = _load_all(
            os.path.join(DEPLOY, "kubernetes", "manager.yaml")
        )
        svc = next(d for d in svc_docs if d["kind"] == "Service")
        labels = svc["metadata"].get("labels", {})
        assert sel.items() <= labels.items(), (
            f"ServiceMonitor selector {sel} does not match manager "
            f"Service labels {labels} — it would scrape nothing"
        )
        # the scraped port name must exist on the Service
        port_names = {p.get("name") for p in svc["spec"]["ports"]}
        for ep in mon["spec"]["endpoints"]:
            assert ep.get("port") in port_names

    def test_network_policy_allows_the_metrics_port(self):
        docs = _load_all(
            os.path.join(DEPLOY, "kubernetes", "network-policy.yaml")
        )
        pol = next(d for d in docs if d["kind"] == "NetworkPolicy")
        ports = {
            p.get("port")
            for rule in pol["spec"].get("ingress", [])
            for p in rule.get("ports", [])
        }
        assert 18081 in ports or "metrics" in ports


class TestSampleCRs:
    @pytest.mark.parametrize(
        "name",
        ["llmservice_cache.yaml", "llmservice_gang.yaml",
         "llmservice_native.yaml", "llmservice_simple.yaml"],
    )
    def test_sample_validates_through_admission(self, name):
        from kubeinfer_tpu.api.types import LLMService

        docs = _load_all(os.path.join(DEPLOY, "samples", name))
        assert docs
        for doc in docs:
            svc = LLMService.from_dict(doc)
            svc.validate()  # raises on an invalid sample


class TestStandbyManifest:
    """manager-standby.yaml: the replica standby's flags must parse
    against the real CLI and wire the replica mode (store-connect +
    data-dir + leader-elect), with its state on a mounted volume."""

    def _dep(self):
        docs = _load_all(
            os.path.join(DEPLOY, "kubernetes", "manager-standby.yaml")
        )
        return next(d for d in docs if d["kind"] == "Deployment")

    def test_args_parse_against_the_real_cli(self):
        from kubeinfer_tpu.manager.__main__ import build_parser

        args = [
            a for c in _containers(self._dep())
            for a in c.get("args", [])
        ]
        assert args
        ns = build_parser().parse_args(args)
        # replica mode = store-connect + data-dir (manager/__init__.py)
        assert ns.store_connect and ns.data_dir and ns.leader_elect

    def test_data_dir_is_on_a_mounted_volume(self):
        from kubeinfer_tpu.manager.__main__ import build_parser

        dep = self._dep()
        c = _containers(dep)[0]
        ns = build_parser().parse_args(c["args"])
        mounts = [m["mountPath"] for m in c.get("volumeMounts", [])]
        assert any(
            ns.data_dir == m or ns.data_dir.startswith(m + "/")
            for m in mounts
        ), (ns.data_dir, mounts)

    def test_standby_connects_to_the_manager_service(self):
        c = _containers(self._dep())[0]
        connect = next(
            a for a in c["args"] if a.startswith("--store-connect=")
        )
        # the Service name from manager.yaml — readiness-gated failover
        # depends on both Deployments sitting behind the same Service
        assert "kubeinfer-manager:18080" in connect
