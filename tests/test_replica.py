"""Store replication: journal-streaming standby + promotion with state.

The etcd-replication role (r4 verdict missing #1): a follower tails the
primary's watch stream into its own durable store, preserving objects
AND the resourceVersion counter verbatim, so a promoted standby carries
the full control plane — CAS/lease-steal continuity included — with no
shared disk. The cross-process story (kill -9 the leader, standby binds
the frontend and the fleet reconverges) lives in test_process_e2e.py;
these tests pin the replication machinery in-process.
"""

from __future__ import annotations

import time

import pytest

from kubeinfer_tpu.controlplane.httpstore import RemoteStore, StoreServer
from kubeinfer_tpu.controlplane.replica import StoreReplica
from kubeinfer_tpu.controlplane.store import Store


def wait_until(pred, timeout, what):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def _obj(name, i=0, ns="default"):
    return {"metadata": {"name": name, "namespace": ns}, "spec": {"i": i}}


class TestReplicatedApply:
    def test_apply_preserves_rv_verbatim(self, tmp_path):
        a = Store()
        b = Store(data_dir=tmp_path / "b")
        w = a.watch()  # capture the full history for verbatim replay
        a.create("Node", _obj("n1"))
        o = a.get("Node", "n1")
        o["spec"]["i"] = 5
        a.update("Node", o)
        a.create("Node", _obj("n2"))
        a.delete("Node", "n2")
        for e in w.drain():
            b.apply_replicated(
                e.type, e.kind, e.namespace, e.name, e.object,
                e.resource_version,
            )
        assert b._rv == a._rv
        assert b.get("Node", "n1") == a.get("Node", "n1")
        with pytest.raises(KeyError):
            b.get("Node", "n2")
        # replayed rvs are idempotent (resync overlap)
        b.apply_replicated("ADDED", "Node", "default", "n1", _obj("n1"), 1)
        assert b.get("Node", "n1")["spec"]["i"] == 5

    def test_replica_survives_restart_with_rv(self, tmp_path):
        b = Store(data_dir=tmp_path / "b")
        b.apply_replicated("ADDED", "Node", "default", "n1", _obj("n1"), 7)
        b.close()
        b2 = Store(data_dir=tmp_path / "b")
        assert b2._rv == 7
        assert b2.get("Node", "n1")["metadata"]["name"] == "n1"

    def test_load_dump_refuses_rv_regression(self, tmp_path):
        b = Store(data_dir=tmp_path / "b")
        b.apply_replicated("ADDED", "Node", "default", "n1", _obj("n1"), 9)
        with pytest.raises(ValueError, match="regress"):
            b.load_dump(3, [["Node", "default", "nx", _obj("nx")]])

    def test_load_dump_atomic_snapshot(self, tmp_path):
        b = Store(data_dir=tmp_path / "b")
        b.load_dump(12, [["Node", "default", "n1", _obj("n1", 3)]])
        b.close()
        b2 = Store(data_dir=tmp_path / "b")
        assert b2._rv == 12
        assert b2.get("Node", "n1")["spec"]["i"] == 3


class TestStoreReplicaFollow:
    def _primary(self, store):
        server = StoreServer(store, "127.0.0.1", 0).start()
        return server, RemoteStore(server.address)

    def test_bootstrap_and_tail(self, tmp_path):
        a = Store()
        # pre-existing state exercises the /dump bootstrap (the event
        # ring never saw these writes from the follower's perspective)
        a.create("Node", _obj("n1", 1))
        a.create("LLMService", _obj("svc", 2))
        server, remote = self._primary(a)
        try:
            rep = StoreReplica(
                RemoteStore(server.address, request_timeout_s=5.0),
                data_dir=tmp_path / "rep", poll_timeout_s=0.3,
            )
            rep.start(lambda: False)
            assert rep.wait_synced(10)
            wait_until(lambda: rep.store._rv == a._rv, 10, "bootstrap")
            # live tail: new writes stream through the watch ring
            o = a.get("Node", "n1")
            o["spec"]["i"] = 42
            a.update("Node", o)
            a.create("Node", _obj("n3"))
            a.delete("LLMService", "svc")
            wait_until(lambda: rep.store._rv == a._rv, 10, "tail")
            assert rep.store.get("Node", "n1")["spec"]["i"] == 42
            assert rep.store.get("Node", "n3")["metadata"]["name"] == "n3"
            with pytest.raises(KeyError):
                rep.store.get("LLMService", "svc")
            rep.stop()
        finally:
            server.shutdown()

    def test_promotion_callback_after_grace(self, tmp_path):
        a = Store()
        a.create("Node", _obj("n1"))
        server, _ = self._primary(a)
        promoted = []

        def on_dead():
            promoted.append(True)
            return True

        rep = StoreReplica(
            RemoteStore(server.address, request_timeout_s=1.0),
            data_dir=tmp_path / "rep",
            failover_grace_s=0.5, poll_timeout_s=0.3,
        )
        rep.start(on_dead)
        try:
            assert rep.wait_synced(10)
            rv_before = rep.store._rv
            server.shutdown()  # primary dies
            wait_until(lambda: rep.promoted.is_set(), 15, "promotion")
            assert promoted
            # the promoted store still carries the primary's state + rv
            assert rep.store._rv == rv_before
            assert rep.store.get("Node", "n1")["metadata"]["name"] == "n1"
            # promoted replica's store stays OPEN (ownership moved to
            # the serving manager)
            rep.stop()
            rep.store.create("Node", _obj("n9"))
            assert rep.store._rv == rv_before + 1
        finally:
            rep.store.close()

    def test_lost_bind_race_resumes_following(self, tmp_path):
        a = Store(data_dir=tmp_path / "a")
        a.create("Node", _obj("n1"))
        server, _ = self._primary(a)
        port_holder = {}
        port_holder["addr"] = server.address

        attempts = []

        def on_dead():
            attempts.append(True)
            if len(attempts) == 1:
                # sibling won the race: a NEW primary appears at a new
                # address... here we just restart one and repoint the
                # follower's remote (same-address semantics in prod)
                return False
            return True

        rep = StoreReplica(
            RemoteStore(server.address, request_timeout_s=1.0),
            data_dir=tmp_path / "rep",
            failover_grace_s=0.4, poll_timeout_s=0.3,
        )
        rep.start(on_dead)
        try:
            assert rep.wait_synced(10)
            server.shutdown()
            wait_until(lambda: len(attempts) >= 2, 20, "second attempt")
            rep.stop()
        finally:
            a.close()

    def test_divergence_repair_adopts_shorter_primary(self, tmp_path):
        """A follower AHEAD of the serving primary (it was better-
        replicated but lost the bind race) must adopt the primary's
        shorter history wholesale — keeping its surplus records would
        silently diverge forever (the primary's events at already-
        passed rvs are filtered out of its watch stream)."""
        seed = Store(data_dir=tmp_path / "rep")
        seed.apply_replicated("ADDED", "Node", "default", "n1", _obj("n1"), 3)
        seed.apply_replicated(
            "ADDED", "LLMService", "default", "ghost", _obj("ghost"), 10
        )
        seed.close()

        a = Store()  # the new primary: shorter history, no ghost
        a.create("Node", _obj("n1", 1))  # rv 1
        server, _ = self._primary(a)
        try:
            rep = StoreReplica(
                RemoteStore(server.address, request_timeout_s=5.0),
                data_dir=tmp_path / "rep", poll_timeout_s=0.3,
            )
            assert rep.store._rv == 10  # replayed the stale surplus
            rep.start(lambda: False)
            wait_until(
                lambda: rep.store._rv == a._rv, 10, "divergence repair"
            )
            with pytest.raises(KeyError):
                rep.store.get("LLMService", "ghost")
            # and the tail is live on the adopted base
            a.create("Node", _obj("n2"))
            wait_until(lambda: rep.store._rv == a._rv, 10, "tail")
            assert rep.store.get("Node", "n2")["metadata"]["name"] == "n2"
            rep.stop()
        finally:
            server.shutdown()

    def test_live_tail_detects_behind_primary(self, tmp_path):
        """A primary restarted with SHORTER history behind the same
        address must be detected from the live tail, not only at
        bootstrap: the watch cursor is clamped to `since`, so detection
        rides the page's storeRv field. The follower adopts the new
        primary's state and resumes tailing it."""
        a = Store()
        a.create("Node", _obj("n1"))
        for i in range(5):
            o = a.get("Node", "n1")
            o["spec"]["i"] = i
            a.update("Node", o)
        server, _ = self._primary(a)
        port = server.port
        rep = StoreReplica(
            RemoteStore(server.address, request_timeout_s=2.0),
            data_dir=tmp_path / "rep",
            failover_grace_s=30.0,  # never promote in this test
            poll_timeout_s=0.2,
        )
        rep.start(lambda: False)
        try:
            assert rep.wait_synced(10)
            wait_until(lambda: rep.store._rv == a._rv, 10, "initial sync")
            assert rep.store._rv == 6
            server.shutdown()
            # fresh primary, same port, shorter history (rv 1)
            b = Store()
            b.create("Node", _obj("n2", 9))
            server2 = StoreServer(b, "127.0.0.1", port).start()
            try:
                wait_until(
                    lambda: rep.store._rv == b._rv, 20,
                    "behind-primary adoption",
                )
                assert rep.store.get("Node", "n2")["spec"]["i"] == 9
                with pytest.raises(KeyError):
                    rep.store.get("Node", "n1")
                # and the tail is live on the adopted base
                b.create("Node", _obj("n3"))
                wait_until(
                    lambda: rep.store._rv == b._rv, 10, "live tail"
                )
            finally:
                server2.shutdown()
        finally:
            rep.stop()
