"""Tensor-parallel sharded serving: the EngineLayout contracts that
let the paged continuous batch run across the 8-device mesh without
anyone being able to tell from the token streams.

- **tp=1 is byte-for-byte degenerate.** The default layout carries no
  mesh and every shard_* hook is the identity — the engine's arrays,
  traces, and compile cache are exactly the pre-sharding engine's.

- **Token parity across layouts.** tp > 1 only PLACES arrays (params
  per the Megatron specs, the KV pool along n_kv, everything else
  replicated); GSPMD partitions the same programs. Streams must match
  tp=1 exactly — greedy and sampled, cold and warm admits, across
  preemption cycles — because sampling keys are position-folded and
  picks ride logit gaps (see EngineLayout's docstring on dominance).

- **Divisibility is a hard door.** Every device owns whole q and KV
  heads (heads % tp == 0 and n_kv % tp == 0); GQA ratios down to
  n_kv == tp (one KV head per device) are in-contract.

- **ICI ordering.** order_devices_ici snakes the chip grid so
  consecutive mesh ranks are one hop apart, and mesh_device_array puts
  tp (the per-step psum axis) on those adjacent positions; coordless
  devices (this suite's virtual CPU mesh) keep enumeration order.

- **Compile discipline.** One compiled shape per (window bucket,
  layout): repeating a seen workload under sharding registers zero
  fresh first-seens, and the pool placement visibly survives donation.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from kubeinfer_tpu.inference import PRESETS, init_params
from kubeinfer_tpu.inference.batching import (
    ContinuousEngine,
    PreemptionPolicy,
)
from kubeinfer_tpu.inference.config import ModelConfig
from kubeinfer_tpu.inference.sharding import (
    EngineLayout,
    mesh_device_array,
    order_devices_ici,
)

TINY = PRESETS["tiny"]  # heads=4, n_kv=2: supports tp in {1, 2}

# GQA shape where tp divides n_kv strictly (tp=2) and exactly
# (tp=4 -> one KV head per device, the contract's floor)
GQA = ModelConfig(
    vocab_size=256, hidden_size=64, intermediate_size=128,
    num_hidden_layers=2, num_attention_heads=8,
    num_key_value_heads=4, max_position_embeddings=512,
)
# MHA shape that stretches to the full 8-device mesh
MHA8 = ModelConfig(
    vocab_size=256, hidden_size=64, intermediate_size=128,
    num_hidden_layers=2, num_attention_heads=8,
    num_key_value_heads=8, max_position_embeddings=512,
)

AGGRESSIVE = PreemptionPolicy(
    threshold_s=0.0005, objective=0.5, burn_limit=0.5,
    cooldown_steps=1, min_progress=1,
)


@pytest.fixture(scope="module")
def params():
    return init_params(TINY, jax.random.PRNGKey(6))


def _engine(params, cfg=TINY, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("cache_len", 64)
    kw.setdefault("block_size", 8)
    return ContinuousEngine(params, cfg, **kw).start()


def _streams(eng, prompt, n=9):
    """Cold greedy + sampled, then a warm (radix-hit) readmit — the
    three admit paths parity must cover."""
    g = eng.generate(prompt, max_new_tokens=n)
    s = eng.generate(prompt, max_new_tokens=n,
                     temperature=0.8, seed=5, top_k=13)
    w = eng.generate(prompt, max_new_tokens=n)
    return g, s, w


class TestEngineLayout:
    def test_default_is_degenerate(self, params):
        lay = EngineLayout()
        assert lay.tp == 1 and lay.mesh is None
        assert not lay.sharded
        assert lay.mesh_devices == 1
        # identity, not a copy: tp=1 must not even touch the arrays
        assert lay.shard_params(params, TINY) is params
        sentinel = object()
        assert lay.shard_state(sentinel) is sentinel
        lay.check_model(TINY)  # no mesh -> nothing to check

    def test_build_tp1_stays_meshless(self):
        assert EngineLayout.build(1).mesh is None
        assert EngineLayout.build(0).mesh is None

    def test_build_makes_tp_mesh(self):
        lay = EngineLayout.build(2)
        assert lay.sharded and lay.mesh_devices == 2
        assert "tp" in lay.mesh.axis_names
        assert lay.pool_sharding().spec == P(None, None, "tp", None)

    def test_mesh_iff_sharded(self):
        with pytest.raises(ValueError, match="mesh"):
            EngineLayout(tp=2, mesh=None)
        with pytest.raises(ValueError, match="mesh"):
            EngineLayout(tp=1, mesh=EngineLayout.build(2).mesh)
        with pytest.raises(ValueError, match=">= 1"):
            EngineLayout(tp=0)

    def test_divisibility_is_a_hard_door(self):
        lay = EngineLayout.build(4)
        # tiny: n_kv=2 < tp=4 — a device would own zero KV heads
        with pytest.raises(ValueError, match="num_key_value_heads"):
            lay.check_model(TINY)
        with pytest.raises(ValueError, match="num_key_value_heads"):
            EngineLayout.build(8).check_model(GQA)  # n_kv=4 < tp=8
        with pytest.raises(ValueError, match="num_attention_heads"):
            EngineLayout.build(3).check_model(MHA8)  # 8 % 3 != 0
        lay.check_model(GQA)  # n_kv == tp is the in-contract floor

    def test_engine_constructor_enforces_the_door(self, params):
        with pytest.raises(ValueError, match="num_key_value_heads"):
            ContinuousEngine(params, TINY, n_slots=2, cache_len=64,
                            block_size=8, layout=EngineLayout.build(4))


class _FakeDev:
    """Stand-in with the three attrs the ordering reads; repr'd by id
    so mismatched walks show as readable sequences."""

    def __init__(self, i, coords, core=0):
        self.id = i
        self.coords = coords
        self.core_on_chip = core

    def __repr__(self):
        return f"d{self.id}"


class TestIciOrdering:
    def test_coordless_devices_keep_enumeration_order(self):
        devs = jax.devices()
        assert order_devices_ici(devs) == list(devs)

    def test_snake_walk_on_2d_grid(self):
        # 4x2 grid in row-major enumeration; the walk must flip
        # direction on odd rows so each step is one ICI hop
        grid = {(x, y): _FakeDev(4 * y + x, (x, y, 0))
                for y in range(2) for x in range(4)}
        walk = order_devices_ici(list(grid.values()))
        coords = [d.coords[:2] for d in walk]
        assert coords == [(0, 0), (1, 0), (2, 0), (3, 0),
                          (3, 1), (2, 1), (1, 1), (0, 1)]
        # every consecutive pair is manhattan-adjacent — the property
        # the walk exists for
        for a, b in zip(coords, coords[1:]):
            assert abs(a[0] - b[0]) + abs(a[1] - b[1]) == 1

    def test_megacore_sorts_chip_adjacent(self):
        devs = [
            _FakeDev(0, (0, 0, 0), core=1), _FakeDev(1, (1, 0, 0), core=0),
            _FakeDev(2, (0, 0, 0), core=0), _FakeDev(3, (1, 0, 0), core=1),
        ]
        assert [d.id for d in order_devices_ici(devs)] == [2, 0, 1, 3]

    def test_tp_ranks_are_chain_adjacent(self):
        grid = [_FakeDev(4 * y + x, (x, y, 0))
                for y in range(2) for x in range(4)]
        arr = mesh_device_array(grid, dp=1, tp=4, sp=2)
        assert arr.shape == (1, 4, 2)
        # fixed sp rank: the 4 tp ranks occupy 4 consecutive chain
        # positions (the snake walk), each one hop from the next
        for s in range(2):
            cs = [d.coords[:2] for d in arr[0, :, s]]
            for a, b in zip(cs, cs[1:]):
                assert abs(a[0] - b[0]) + abs(a[1] - b[1]) == 1

    def test_sp1_matches_historical_layout(self):
        devs = jax.devices()
        arr = mesh_device_array(devs, dp=2, tp=4, sp=1)
        assert arr.shape == (2, 4, 1)
        # sp==1 transpose is the identity: plain row-major fill
        assert list(arr.reshape(-1)) == list(devs)


class TestShardedParity:
    def test_tp2_matches_tp1_cold_and_warm(self, params):
        rng = np.random.default_rng(21)
        prompt = rng.integers(0, TINY.vocab_size, 7).tolist()
        ref = _engine(params, max_window=8)
        try:
            want = _streams(ref, prompt)
        finally:
            ref.stop()
        eng = _engine(params, max_window=8, layout=EngineLayout.build(2))
        try:
            got = _streams(eng, prompt)
            # the pool placement survived admits + donated windows
            # (semantic compare: GSPMD trims trailing None dims)
            pool_ok = eng._state.caches_k[0].sharding.is_equivalent_to(
                eng.layout.pool_sharding(), 4
            )
            stats = eng.stats_summary()
        finally:
            eng.stop()
        assert got == want
        assert pool_ok
        assert stats["tp_degree"] == 2 and stats["mesh_devices"] == 2

    def test_gqa_ratios_divide_and_equal(self):
        gparams = init_params(GQA, jax.random.PRNGKey(7))
        rng = np.random.default_rng(22)
        prompt = rng.integers(0, GQA.vocab_size, 6).tolist()
        want = None
        for tp in (1, 2, 4):  # tp=4: n_kv == tp, one KV head/device
            eng = _engine(gparams, cfg=GQA, max_window=4,
                          layout=EngineLayout.build(tp))
            try:
                got = _streams(eng, prompt, n=7)
            finally:
                eng.stop()
            if want is None:
                want = got
            else:
                assert got == want, f"tp={tp} diverged"

    def test_preemption_parity_under_sharding(self, params):
        """Park/resume cycles with the pool sharded: parks scatter KV
        out of a sharded pool and resumes gather back in — streams must
        still match the uncontended sharded engine."""
        rng = np.random.default_rng(23)
        prompts = [rng.integers(0, TINY.vocab_size, 5).tolist()
                   for _ in range(8)]
        kw = lambda i: dict(  # noqa: E731 - tiny per-index sampler knobs
            temperature=0.8 if i % 2 else 0.0,
            seed=50 + i, top_k=9 if i % 2 else 0,
        )
        solo = _engine(params, max_window=8, layout=EngineLayout.build(2))
        try:
            want = [solo.generate(p, max_new_tokens=8, **kw(i))
                    for i, p in enumerate(prompts)]
        finally:
            solo.stop()
        eng = _engine(params, max_window=8, preemption=AGGRESSIVE,
                      layout=EngineLayout.build(2))
        try:
            reqs = [eng.submit(p, max_new_tokens=8, **kw(i))
                    for i, p in enumerate(prompts)]
            for i, r in enumerate(reqs):
                assert r.done.wait(300), f"request {i} starved"
                assert not r.failed
            preempted = eng.preempted_total
        finally:
            eng.stop()
        assert preempted >= 1, "policy never parked anything"
        for i, r in enumerate(reqs):
            assert r.out_tokens == want[i], f"request {i}"

    @pytest.mark.slow
    def test_full_mesh_tp8(self):
        mparams = init_params(MHA8, jax.random.PRNGKey(8))
        rng = np.random.default_rng(24)
        prompt = rng.integers(0, MHA8.vocab_size, 6).tolist()
        ref = _engine(mparams, cfg=MHA8, max_window=4)
        try:
            want = _streams(ref, prompt, n=7)
        finally:
            ref.stop()
        eng = _engine(mparams, cfg=MHA8, max_window=4,
                      layout=EngineLayout.build(8))
        try:
            got = _streams(eng, prompt, n=7)
        finally:
            eng.stop()
        assert got == want

    @pytest.mark.slow
    def test_bf16_parity(self):
        """Same dominance argument at lower precision: both layouts
        quantize identically because placement never rewrites math."""
        import jax.numpy as jnp

        bparams = init_params(TINY, jax.random.PRNGKey(9),
                              dtype=jnp.bfloat16)
        rng = np.random.default_rng(25)
        prompt = rng.integers(0, TINY.vocab_size, 6).tolist()
        ref = _engine(bparams, max_window=4)
        try:
            want = _streams(ref, prompt, n=7)
        finally:
            ref.stop()
        eng = _engine(bparams, max_window=4,
                      layout=EngineLayout.build(2))
        try:
            got = _streams(eng, prompt, n=7)
        finally:
            eng.stop()
        assert got == want


class TestCompileDiscipline:
    @pytest.mark.slow
    def test_one_shape_per_bucket_per_layout(self, params):
        """Under sharding the compile key gains the layout, nothing
        else: the first pass pays one compile per shape, repeating the
        exact workload registers ZERO fresh (phase, bucket) first-seens
        — donation kept the carry shardings stable."""
        rng = np.random.default_rng(26)
        prompt = rng.integers(0, TINY.vocab_size, 9).tolist()
        eng = _engine(params, max_window=8, layout=EngineLayout.build(2))
        try:
            eng.generate(prompt, max_new_tokens=12)  # 11 post-admit: 8+2+1
            buckets = {r.bucket for r in eng.profiler.snapshot()
                       if r.phase == "decode"}
            assert buckets == {8, 2, 1}
            c0 = eng.profiler.compile_count
            eng.generate(prompt, max_new_tokens=12)
            assert eng.profiler.compile_count == c0
            # fresh bucket (4) is exactly one new first-seen
            eng.generate(prompt, max_new_tokens=6)
            assert eng.profiler.compile_count == c0 + 1
            eng.generate(prompt, max_new_tokens=6)
            assert eng.profiler.compile_count == c0 + 1
        finally:
            eng.stop()
