"""Trace-driven load harness: seeded open-loop arrival generation.

Pins the envelope-observatory determinism contract: the arrival
schedule is a pure function of (process, rate, n, seed) — same seed,
byte-identical schedule, with a cross-process golden checksum so a
refactor that silently reorders the RNG draw sequence fails loudly.
The replay loop is exercised against a stub fleet: open-loop pacing,
errors as data points, server-stamped latency fields.
"""

from __future__ import annotations

import concurrent.futures
import threading

import numpy as np
import pytest

from kubeinfer_tpu.observability import loadgen, tracing

# cross-process pin: make_schedule(proc, rate=10.0, n_requests=50,
# seed=7) hashed over the canonical per-request lines. Regenerating
# these is a format break — downstream runs key artifact identity on
# them (see ArrivalSchedule.checksum).
GOLDEN = {
    "poisson":
        "c8623da30519a32eed9dbb766bfc88f654f1adb357a4d31f3c5f02f91b07ba20",
    "diurnal":
        "dc90e272c7f016c390eef9745d94d30078fb26164a79d16738de807179142140",
    "burst":
        "50fb825cc8ecb7cf23c64fb91ff7147b4f398b59db0ef099680e569c0b7e631c",
}


class TestScheduleDeterminism:
    @pytest.mark.parametrize("proc", loadgen.PROCESSES)
    def test_same_seed_identical_schedule(self, proc):
        a = loadgen.make_schedule(proc, rate=20.0, n_requests=200, seed=3)
        b = loadgen.make_schedule(proc, rate=20.0, n_requests=200, seed=3)
        assert a.requests == b.requests
        assert a.checksum() == b.checksum()

    @pytest.mark.parametrize("proc", loadgen.PROCESSES)
    def test_golden_checksum_pin(self, proc):
        s = loadgen.make_schedule(proc, rate=10.0, n_requests=50, seed=7)
        assert s.checksum() == GOLDEN[proc]

    def test_seed_and_process_move_the_checksum(self):
        base = loadgen.make_schedule("poisson", rate=10.0,
                                     n_requests=50, seed=7)
        other = loadgen.make_schedule("poisson", rate=10.0,
                                      n_requests=50, seed=8)
        assert base.checksum() != other.checksum()
        assert base.checksum() != GOLDEN["burst"]

    def test_prompt_tokens_deterministic_and_group_shared(self):
        s = loadgen.make_schedule("poisson", rate=10.0, n_requests=400,
                                  seed=11, long_frac=0.5)
        by_group: dict[int, list] = {}
        for r in s.requests:
            by_group.setdefault(r.group, []).append(r)
        grp = next(v for v in by_group.values()
                   if sum(r.family == "long" for r in v) >= 2)
        longs = [r for r in grp if r.family == "long"][:2]
        ta = s.prompt_tokens(longs[0], 1000)
        tb = s.prompt_tokens(longs[1], 1000)
        assert ta == s.prompt_tokens(longs[0], 1000)  # pure function
        assert len(ta) == longs[0].prompt_len
        # same group => same prefix head (the radix-cache bait), tails
        # drawn per-request
        head = min(longs[0].prompt_len // 2, 64)
        assert ta[:head] == tb[:head]
        assert ta[head:] != tb[head:]


class TestLengthFamilies:
    def test_family_draws_match_round9_heavy_tail(self):
        s = loadgen.make_schedule("poisson", rate=50.0, n_requests=2000,
                                  seed=5, long_frac=0.2)
        longs = [r for r in s.requests if r.family == "long"]
        shorts = [r for r in s.requests if r.family == "short"]
        assert {r.prompt_len for r in longs} <= {480, 496, 512}
        assert all(8 <= r.prompt_len <= 16 for r in shorts)
        assert len(longs) + len(shorts) == 2000
        # law of large numbers, not a distribution test: 20% +- 5pt
        assert 0.15 < len(longs) / 2000 < 0.25

    def test_arrivals_sorted_and_rate_honest(self):
        for proc in loadgen.PROCESSES:
            s = loadgen.make_schedule(proc, rate=40.0, n_requests=1000,
                                      seed=2)
            ts = [r.t for r in s.requests]
            assert ts == sorted(ts)
            assert ts[0] >= 0.0
            # offered rate derives from the realized span; for poisson
            # it concentrates near the nominal rate
            if proc == "poisson":
                assert s.offered_req_per_s() == pytest.approx(40.0,
                                                              rel=0.2)

    def test_input_validation(self):
        with pytest.raises(ValueError):
            loadgen.make_schedule("lunar", rate=1.0, n_requests=1)
        with pytest.raises(ValueError):
            loadgen.make_schedule("poisson", rate=0.0, n_requests=1)
        with pytest.raises(ValueError):
            loadgen.make_schedule("poisson", rate=1.0, n_requests=0)


class TestReplay:
    def _schedule(self, n=40, rate=400.0, seed=13):
        return loadgen.make_schedule("poisson", rate=rate, n_requests=n,
                                     seed=seed)

    def test_replay_records_server_stamped_fields(self):
        s = self._schedule()

        def post(body):
            return {
                "usage": {"completion_tokens": body["max_tokens"]},
                "kubeinfer": {"ttft_ms": 5.0, "tpot_ms": 1.0,
                              "replica": "r0"},
            }

        res = loadgen.replay(s, post, vocab_size=100, speed=100.0)
        assert len(res.records) == len(s.requests)
        assert len(res.completed()) == len(s.requests)
        assert res.errors() == 0
        assert res.ttft_ms_percentile(99.0) == pytest.approx(5.0)
        assert res.goodput_tokens_per_s() > 0.0
        recs = sorted(res.records, key=lambda r: r.index)
        for rec, req in zip(recs, s.requests):
            assert rec.replica == "r0"
            assert rec.tokens_out == req.max_new
            assert rec.trace_id  # joined to fleet spans by this id

    def test_errors_are_data_points_not_run_failures(self):
        s = self._schedule(n=20)
        calls = {"n": 0}
        lock = threading.Lock()

        def post(body):
            with lock:
                calls["n"] += 1
                if calls["n"] % 2 == 0:
                    raise RuntimeError("HTTP 503")
            return {"usage": {"completion_tokens": 1},
                    "kubeinfer": {"ttft_ms": 1.0}}

        res = loadgen.replay(s, post, vocab_size=100, speed=100.0)
        assert len(res.completed()) == 10
        assert res.errors() == 10
        errs = [r for r in res.records if not r.ok]
        assert all(e.error == "RuntimeError: HTTP 503" for e in errs)

    def test_empty_percentile_is_nan_not_crash(self):
        s = self._schedule(n=5)

        def post(body):
            raise RuntimeError("down")

        res = loadgen.replay(s, post, vocab_size=100, speed=100.0)
        p = res.ttft_ms_percentile(99.0)
        assert p != p  # NaN

    def test_replay_spans_carry_the_join_key(self):
        s = self._schedule(n=6)
        tracing.RECORDER.clear()

        def post(body):
            return {"usage": {"completion_tokens": 1},
                    "kubeinfer": {"ttft_ms": 1.0}}

        res = loadgen.replay(s, post, vocab_size=100, speed=100.0)
        roots = [sp for sp in tracing.RECORDER.snapshot()
                 if sp.name == "client.request"]
        assert {sp.trace_id for sp in roots} == \
            {r.trace_id for r in res.records}


@pytest.mark.slow
class TestFullScaleSweep:
    """O(1e5) leg: schedule generation and replay at the advertised
    scale, with head sampling keeping the span ring from swallowing the
    run. Stubbed fleet — the real-engine envelope lives in
    test_observability_envelope.py; this pins the harness itself."""

    def test_1e5_requests_deterministic_and_replayable(self):
        n = 100_000
        a = loadgen.make_schedule("diurnal", rate=2000.0, n_requests=n,
                                  seed=17)
        b = loadgen.make_schedule("diurnal", rate=2000.0, n_requests=n,
                                  seed=17)
        assert a.checksum() == b.checksum()
        assert len(a.requests) == n

        done = {"n": 0}
        lock = threading.Lock()

        def post(body):
            with lock:
                done["n"] += 1
            return {"usage": {"completion_tokens": body["max_tokens"]},
                    "kubeinfer": {"ttft_ms": 2.0, "tpot_ms": 0.5,
                                  "replica": "r0"}}

        prev = tracing.set_span_sampling(64)
        try:
            res = loadgen.replay(a, post, vocab_size=1000,
                                 speed=100_000.0, max_workers=64)
        finally:
            tracing.set_span_sampling(prev)
        assert done["n"] == n
        assert len(res.completed()) == n
        assert res.goodput_tokens_per_s() > 0.0
