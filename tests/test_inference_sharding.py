"""Sharded inference paths vs the single-device reference.

All on the virtual 8-device CPU mesh (conftest): tensor parallel must be
numerically identical (same math, psum-reassembled), ring attention must
equal dense attention (same softmax, blockwise), and the SP forward must
match the dense forward end to end.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from kubeinfer_tpu.utils.jaxcompat import shard_map
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from kubeinfer_tpu.inference import PRESETS, forward, init_params
from kubeinfer_tpu.inference.ring_attention import ring_attention
from kubeinfer_tpu.inference.model import attention, causal_mask
from kubeinfer_tpu.inference.sharding import (
    forward_sequence_parallel,
    forward_tensor_parallel,
    make_inference_mesh,
)

TINY = PRESETS["tiny"]


def tokens_for(B=2, T=16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        rng.integers(0, TINY.vocab_size, (B, T)).astype(np.int32)
    )


class TestMesh:
    def test_mesh_shapes(self):
        mesh = make_inference_mesh(tp=2, sp=2)
        assert dict(mesh.shape) == {"dp": 2, "tp": 2, "sp": 2}

    def test_oversized_mesh_rejected(self):
        with pytest.raises(ValueError):
            make_inference_mesh(tp=16)


class TestTensorParallel:
    def test_tp_matches_single_device(self):
        params = init_params(TINY, jax.random.PRNGKey(0))
        toks = tokens_for()
        ref, _ = forward(params, toks, TINY)
        mesh = make_inference_mesh(tp=4, sp=1)
        out = forward_tensor_parallel(params, toks, TINY, mesh)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
        )

    def test_tp_with_qkv_bias_matches_single_device(self):
        # Qwen2-family biases must shard with their projections' output
        # axis (param_specs' qkv_bias branch) and stay numerically exact
        from kubeinfer_tpu.inference import ModelConfig

        cfg = ModelConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, qkv_bias=True,
        )
        key = jax.random.PRNGKey(3)
        params = init_params(cfg, key)
        # nonzero biases, or the test cannot distinguish bias sharding
        # from no bias at all
        for layer in params["layers"]:
            for b in ("q_bias", "k_bias", "v_bias"):
                key, sub = jax.random.split(key)
                layer[b] = 0.1 * jax.random.normal(
                    sub, layer[b].shape, layer[b].dtype
                )
        toks = jnp.asarray(
            np.random.default_rng(5).integers(0, 128, (2, 8)), jnp.int32
        )
        ref, _ = forward(params, toks, cfg)
        mesh = make_inference_mesh(tp=4, sp=1)
        out = forward_tensor_parallel(params, toks, cfg, mesh)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
        )


    def test_tp_with_moe_matches_single_device(self):
        # Mixtral-family TP: expert ffns shard like the dense mlp with
        # the expert axis replicated (param_specs' moe branch)
        from kubeinfer_tpu.inference import ModelConfig

        cfg = ModelConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, num_local_experts=4,
            num_experts_per_tok=2,
        )
        params = init_params(cfg, jax.random.PRNGKey(7))
        toks = jnp.asarray(
            np.random.default_rng(8).integers(0, 128, (2, 8)), jnp.int32
        )
        ref, _ = forward(params, toks, cfg)
        mesh = make_inference_mesh(tp=4, sp=1)
        out = forward_tensor_parallel(params, toks, cfg, mesh)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
        )



class TestRingAttention:
    def test_ring_equals_dense(self):
        B, T, n_heads, n_kv, D = 2, 32, 4, 2, 16
        rng = np.random.default_rng(5)
        q = jnp.asarray(rng.normal(size=(B, T, n_heads, D)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, T, n_kv, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, T, n_kv, D)), jnp.float32)
        mask = jnp.broadcast_to(causal_mask(T)[None], (B, T, T))
        ref = attention(q, k, v, mask)

        devices = np.asarray(jax.devices()[:8]).reshape(8)
        mesh = Mesh(devices, axis_names=("sp",))
        ring = jax.jit(
            shard_map(
                lambda q, k, v: ring_attention(q, k, v, axis_name="sp"),
                mesh=mesh,
                in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
                out_specs=P(None, "sp"),
            )
        )
        out = ring(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    def test_ring_non_causal(self):
        B, T, n_heads, n_kv, D = 1, 16, 2, 2, 8
        rng = np.random.default_rng(6)
        q = jnp.asarray(rng.normal(size=(B, T, n_heads, D)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, T, n_kv, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, T, n_kv, D)), jnp.float32)
        full = jnp.ones((B, T, T), bool)
        ref = attention(q, k, v, full)
        devices = np.asarray(jax.devices()[:4]).reshape(4)
        mesh = Mesh(devices, axis_names=("sp",))
        ring = jax.jit(
            shard_map(
                lambda q, k, v: ring_attention(
                    q, k, v, axis_name="sp", causal=False
                ),
                mesh=mesh,
                in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
                out_specs=P(None, "sp"),
            )
        )
        np.testing.assert_allclose(
            np.asarray(ring(q, k, v)), np.asarray(ref), rtol=1e-5, atol=1e-5
        )


class TestSequenceParallelForward:
    def test_sp_forward_matches_dense(self):
        params = init_params(TINY, jax.random.PRNGKey(1))
        toks = tokens_for(B=2, T=32, seed=9)
        ref, _ = forward(params, toks, TINY)
        mesh = make_inference_mesh(tp=1, sp=8, dp=1)
        out = forward_sequence_parallel(params, toks, TINY, mesh)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
        )

    def test_sp_rejects_indivisible_seq(self):
        params = init_params(TINY, jax.random.PRNGKey(1))
        mesh = make_inference_mesh(tp=1, sp=8, dp=1)
        with pytest.raises(ValueError, match="divide"):
            forward_sequence_parallel(params, tokens_for(T=30), TINY, mesh)


class TestManualTPMoE:
    """The manual-TP MoE branch (model.decoder_layer tp_axis on a layer
    with routed experts): experts column/row-shard like the dense mlp,
    the router sees replicated activations, and ONE psum after the
    expert-weighted sum completes the row-parallel down contraction —
    executed here under shard_map, not just asserted in comments."""

    def test_moe_forward_matches_unsharded(self):
        import dataclasses
        import functools

        import numpy as np
        from jax.sharding import PartitionSpec as P

        from kubeinfer_tpu.inference import PRESETS, init_params
        from kubeinfer_tpu.inference.model import forward
        from kubeinfer_tpu.inference.sharding import (
            make_axis_mesh,
            param_specs,
        )

        cfg = dataclasses.replace(
            PRESETS["tiny"], num_local_experts=4, num_experts_per_tok=2
        )
        params = init_params(cfg, jax.random.PRNGKey(2))
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(
            rng.integers(1, cfg.vocab_size, (1, 16)), jnp.int32
        )
        want, _ = forward(params, tokens, cfg)

        mesh = make_axis_mesh("tp", 2)
        pspecs = param_specs(cfg)

        def body(p, t):
            out, _ = forward(p, t, cfg, tp_axis="tp", tp_size=2)
            return out

        fn = jax.jit(
            shard_map(
                body, mesh=mesh,
                in_specs=(pspecs, P()),
                out_specs=P(None, None, "tp"),  # lm_head vocab-sharded
            )
        )
        got = fn(params, tokens)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )
