"""Runtime + offline legs of the lifecycle protocol verifier (ISSUE 17).

Per-transition conformance fixtures drive ``replay_events`` with raw
event dicts (the ``to_dict()`` wire shape), the live-monitor tests
drive a real FlightRecorder through ``set_monitor``, and the CLI test
execs ``python -m kubeinfer_tpu.analysis protocol`` as a subprocess —
mirroring tests/test_static_analysis.py's exit-code contract.
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import pytest

from kubeinfer_tpu.analysis import protocol
from kubeinfer_tpu.observability import flightrecorder
from kubeinfer_tpu.observability.flightrecorder import FlightRecorder

REPO = Path(__file__).resolve().parent.parent

_SEQ = iter(range(10_000))


def ev(kind: str, **detail) -> dict:
    seq = next(_SEQ)
    return {"seq": seq, "t": float(seq), "kind": kind, "detail": detail}


def sub(rid: int) -> dict:
    return ev("submit", req=rid, prompt_tokens=8, max_new=4)


def rules_of(rep: protocol.ProtocolReport) -> list[str]:
    return [v.rule for v in rep.violations]


# --- replay: per-transition conformance ------------------------------------


def test_legal_chain_conformant():
    rep = protocol.replay_events([
        sub(1),
        ev("chunk", req=1, slot=0),
        ev("admit", req=1, slot=0),
        ev("preempt", req=1, slot=0),
        ev("resume", req=1, slot=0),
        ev("retire", req=1, slot=0, tokens=4),
    ])
    assert rules_of(rep) == []
    assert rep.chains == {1: "done"}
    assert rep.open_chains() == []


def test_double_terminal_flagged():
    rep = protocol.replay_events([
        sub(1),
        ev("admit", req=1, slot=0),
        ev("retire", req=1, slot=0, tokens=4),
        ev("fail", req=1, reason="also failed?"),
    ])
    assert rules_of(rep) == ["after-terminal"]
    v = rep.violations[0]
    # both event sites ride the violation for the post-mortem
    assert v.event["kind"] == "fail" and v.prev["kind"] == "retire"
    assert "retire" in v.render() and "fail" in v.render()


def test_emit_after_terminal_flagged():
    rep = protocol.replay_events([
        sub(2),
        ev("fail", req=2, reason="boom"),
        ev("chunk", req=2, slot=0),
    ])
    assert rules_of(rep) == ["after-terminal"]


def test_missing_required_detail_flagged():
    rep = protocol.replay_events([
        ev("submit", req=3),  # lacks prompt_tokens, max_new
    ])
    assert rules_of(rep) == ["missing-detail"]
    assert "prompt_tokens" in rep.violations[0].message


def test_unknown_kind_flagged():
    rep = protocol.replay_events([ev("reboot")])
    assert rules_of(rep) == ["unknown-kind"]


def test_illegal_transition_flagged_with_both_sites():
    rep = protocol.replay_events([
        sub(4),
        ev("preempt", req=4, slot=0),  # preempt only from active
    ])
    assert rules_of(rep) == ["illegal-transition"]
    v = rep.violations[0]
    assert v.prev["kind"] == "submit" and v.event["kind"] == "preempt"


def test_chain_start_requires_submit():
    rep = protocol.replay_events([ev("admit", req=5, slot=0)])
    assert rules_of(rep) == ["chain-start"]


def test_truncated_ring_adopts_mid_chain():
    # same stream, but the ring dropped the head: the chain adopts the
    # implied state and checking continues from there
    rep = protocol.replay_events(
        [ev("admit", req=5, slot=0),
         ev("retire", req=5, slot=0, tokens=4)],
        truncated=True,
    )
    assert rules_of(rep) == []
    assert rep.chains == {5: "done"}


def test_backpressure_loops_in_queued():
    rep = protocol.replay_events([
        sub(6),
        ev("backpressure", req=6, reason="pool"),
        ev("backpressure", req=6, reason="pool"),
        ev("admit", req=6, slot=0),
        ev("retire", req=6, slot=0, tokens=4),
    ])
    assert rules_of(rep) == []


# --- replay: drain-window guard --------------------------------------------


def test_migrate_outside_drain_window_flagged():
    rep = protocol.replay_events([
        sub(7),
        ev("migrate", req=7, blocks=0),
    ])
    assert rules_of(rep) == ["guard-draining"]


def test_migrate_inside_drain_window_clean():
    rep = protocol.replay_events([
        sub(7),
        ev("admit", req=7, slot=0),
        ev("drain_start"),
        ev("migrate_chunk", req=7, slot=0, blocks=1),
        ev("migrate_sink_error", req=7, slot=0),
        ev("migrate", req=7, blocks=1),
        ev("drain_end"),
    ])
    assert rules_of(rep) == []
    assert rep.chains == {7: "migrated"}


def test_drain_end_closes_window():
    rep = protocol.replay_events([
        sub(8),
        ev("admit", req=8, slot=0),
        ev("drain_start"),
        ev("drain_end"),
        ev("migrate_chunk", req=8, slot=0, blocks=1),
    ])
    assert rules_of(rep) == ["guard-draining"]


def test_guard_stands_down_on_truncated_ring():
    # the drain_start may be among the evicted events — a truncated
    # replay must not manufacture guard violations
    rep = protocol.replay_events(
        [ev("migrate_chunk", req=9, slot=0, blocks=1)], truncated=True,
    )
    assert rules_of(rep) == []


# --- replay_dump + assert_conformant ---------------------------------------


def test_replay_dump_detects_truncation():
    events = [ev("admit", req=10, slot=0)]
    rep = protocol.replay_dump(
        {"capacity": 1, "recorded": 5, "events": events}
    )
    assert rep.truncated and rules_of(rep) == []
    rep = protocol.replay_dump({"recorded": 1, "events": events})
    assert not rep.truncated and rules_of(rep) == ["chain-start"]


def test_assert_conformant_catches_open_chain_and_phantoms():
    done = [sub(0), ev("admit", req=0, slot=0),
            ev("retire", req=0, slot=0, tokens=4)]
    protocol.assert_conformant(done, expect=[0])
    with pytest.raises(AssertionError, match="terminal"):
        protocol.assert_conformant(done + [sub(1)])
    with pytest.raises(AssertionError, match="expected"):
        protocol.assert_conformant(done, expect=[0, 1])


# --- live monitor -----------------------------------------------------------


def test_monitor_clean_on_conformant_stream():
    fr = FlightRecorder(name="test.ProtoMon.l1")
    mon = protocol.ProtocolMonitor()
    prev = flightrecorder.get_monitor()
    flightrecorder.set_monitor(mon)
    try:
        fr.note("submit", req=1, prompt_tokens=8, max_new=4)
        fr.note("admit", req=1, slot=0)
        fr.note("retire", req=1, slot=0, tokens=4)
    finally:
        flightrecorder.set_monitor(prev)
    mon.assert_clean()


def test_monitor_records_violation_without_raising():
    fr = FlightRecorder(name="test.ProtoMon.l2")
    mon = protocol.ProtocolMonitor()
    prev = flightrecorder.get_monitor()
    flightrecorder.set_monitor(mon)
    try:
        fr.note("submit", req=1, prompt_tokens=8, max_new=4)
        # lint: allow[protocol-order] the illegal transition is the behavior under test
        fr.note("preempt", req=1, slot=0)  # must not raise in note()
    finally:
        flightrecorder.set_monitor(prev)
    assert [v.rule for v in mon.violations] == ["illegal-transition"]
    with pytest.raises(AssertionError, match="illegal-transition"):
        mon.assert_clean()


def test_monitor_keys_chains_per_recorder():
    # the same request id on two recorders is two engines' chains, not
    # one corrupted chain
    fr_a = FlightRecorder(name="test.ProtoMon.l3")
    fr_b = FlightRecorder(name="test.ProtoMon.l4")
    mon = protocol.ProtocolMonitor()
    prev = flightrecorder.get_monitor()
    flightrecorder.set_monitor(mon)
    try:
        fr_a.note("submit", req=1, prompt_tokens=8, max_new=4)
        # lint: allow[protocol-order] DIFFERENT recorders: the static pass sees one method, the monitor keys per recorder
        fr_b.note("submit", req=1, prompt_tokens=8, max_new=4)
        fr_a.note("admit", req=1, slot=0)
        # lint: allow[protocol-order] DIFFERENT recorders: the static pass sees one method, the monitor keys per recorder
        fr_b.note("admit", req=1, slot=0)
    finally:
        flightrecorder.set_monitor(prev)
    mon.assert_clean()


# --- offline CLI ------------------------------------------------------------


def _dump(events: list[dict]) -> dict:
    return {"capacity": 512, "recorded": len(events), "events": events}


def test_cli_exit_codes(tmp_path):
    good = tmp_path / "good_flight.json"
    good.write_text(json.dumps(_dump([
        sub(0), ev("admit", req=0, slot=0),
        ev("retire", req=0, slot=0, tokens=4),
    ])))
    proc = subprocess.run(
        [sys.executable, "-m", "kubeinfer_tpu.analysis", "protocol",
         str(good)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr
    assert "0 violation(s)" in proc.stderr

    bad = tmp_path / "bad_flight.json"
    bad.write_text(json.dumps(_dump([
        sub(1), ev("retire", req=1, slot=0, tokens=4),
        ev("admit", req=1, slot=0),
    ])))
    proc = subprocess.run(
        [sys.executable, "-m", "kubeinfer_tpu.analysis", "protocol",
         str(bad)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 1
    # the first illegal transition is reported with BOTH event sites
    assert "FIRST VIOLATION" in proc.stdout
    assert "after [" in proc.stdout

    garbled = tmp_path / "garbled.json"
    garbled.write_text("{not json")
    proc = subprocess.run(
        [sys.executable, "-m", "kubeinfer_tpu.analysis", "protocol",
         str(garbled)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 2
    assert "unreadable" in proc.stderr
