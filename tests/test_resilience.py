"""Unit tests for the resilience core: RetryPolicy backoff/deadline/
classification and CircuitBreaker transitions.

Scheduling is exercised through injected rng/clock/sleep so every
assertion is deterministic — no wall-clock sleeps, no flaky timing.
Metric assertions measure DELTAS (the process-global registry is shared
with other tests in the session).
"""

from __future__ import annotations

import email.message
import io
import json
import random
import urllib.error

import pytest

from kubeinfer_tpu import metrics
from kubeinfer_tpu.resilience import (
    BreakerOpenError,
    CircuitBreaker,
    RetryPolicy,
    connect_failure,
    is_transport_error,
    transient_http,
)


def _http_error(code: int) -> urllib.error.HTTPError:
    return urllib.error.HTTPError(
        "http://test.invalid/", code, "injected", email.message.Message(),
        io.BytesIO(b"{}"),
    )


class FakeClock:
    """Monotonic clock whose sleep() advances it — retry schedules run
    in zero wall time."""

    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def sleep(self, d: float) -> None:
        assert d >= 0
        self.t += d


# --- classifiers -----------------------------------------------------------


class TestClassifiers:
    def test_transient_http_status_codes(self):
        for code in (429, 500, 502, 503, 504):
            assert transient_http(_http_error(code))
        for code in (400, 401, 404, 409, 501):
            assert not transient_http(_http_error(code))

    def test_transient_http_connection_errors(self):
        assert transient_http(ConnectionResetError())
        assert transient_http(TimeoutError())
        assert transient_http(urllib.error.URLError(ConnectionRefusedError()))
        # a torn JSON body is a transport failure even though json
        # surfaces it as a ValueError subclass...
        assert transient_http(json.JSONDecodeError("x", "{", 1))
        # ...but plain ValueErrors (domain errors subclass it) are NOT
        assert not transient_http(ValueError("already exists"))
        assert not transient_http(KeyError("k"))

    def test_connect_failure_is_narrower(self):
        assert connect_failure(ConnectionRefusedError())
        assert connect_failure(urllib.error.URLError(ConnectionRefusedError()))
        # these may have reached the server — a mutation must not replay
        assert not connect_failure(ConnectionResetError())
        assert not connect_failure(TimeoutError())
        assert not connect_failure(_http_error(503))

    def test_breaker_open_error_is_connectionerror(self):
        # existing `except OSError` handlers must absorb fast-fails
        assert issubclass(BreakerOpenError, ConnectionError)
        assert is_transport_error(BreakerOpenError("open"))


# --- RetryPolicy -----------------------------------------------------------


class TestRetryPolicy:
    def test_jitter_bounds_and_determinism(self):
        p = RetryPolicy(base_delay_s=0.1, max_delay_s=2.0)
        rng = random.Random(7)
        delays = [p.backoff(a, rng) for a in range(8) for _ in range(50)]
        for a in range(8):
            cap = min(2.0, 0.1 * 2**a)
            for d in delays[a * 50:(a + 1) * 50]:
                assert 0.0 <= d <= cap
        # full jitter actually spreads (not constant/equal-delay backoff)
        assert len({round(d, 9) for d in delays[:50]}) > 10
        # same seed → identical schedule
        rng2 = random.Random(7)
        assert delays == [p.backoff(a, rng2) for a in range(8) for _ in range(50)]

    def test_success_after_failures_counts_retries(self):
        clk = FakeClock()
        calls = []

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionResetError("blip")
            return 42

        before = metrics.retry_attempts_total.value("unit.t1")
        p = RetryPolicy(max_attempts=5, base_delay_s=0.01, deadline_s=0)
        out = p.call(fn, edge="unit.t1", rng=random.Random(1),
                     sleep=clk.sleep, clock=clk)
        assert out == 42
        assert len(calls) == 3
        assert metrics.retry_attempts_total.value("unit.t1") - before == 2

    def test_non_retryable_passes_through_first_attempt(self):
        calls = []

        def fn():
            calls.append(1)
            raise ValueError("domain error")

        p = RetryPolicy(max_attempts=5, deadline_s=0)
        with pytest.raises(ValueError):
            p.call(fn, rng=random.Random(0), sleep=lambda d: None)
        assert len(calls) == 1

        # classify narrows further: a reset is transient for GETs but
        # not under connect_failure (the mutation classifier)
        calls.clear()

        def reset():
            calls.append(1)
            raise ConnectionResetError("maybe landed")

        pm = RetryPolicy(max_attempts=5, deadline_s=0, classify=connect_failure)
        with pytest.raises(ConnectionResetError):
            pm.call(reset, rng=random.Random(0), sleep=lambda d: None)
        assert len(calls) == 1

    def test_attempt_budget_exhaustion_raises_original(self):
        clk = FakeClock()
        calls = []

        def fn():
            calls.append(1)
            raise _http_error(503)

        before = metrics.retries_exhausted_total.value("unit.t2")
        p = RetryPolicy(max_attempts=3, base_delay_s=0.01, deadline_s=0)
        with pytest.raises(urllib.error.HTTPError):
            p.call(fn, edge="unit.t2", rng=random.Random(2),
                   sleep=clk.sleep, clock=clk)
        assert len(calls) == 3
        assert metrics.retries_exhausted_total.value("unit.t2") - before == 1

    def test_deadline_caps_schedule(self):
        clk = FakeClock()
        calls = []

        def fn():
            calls.append(1)
            clk.t += 0.4  # each attempt costs 0.4s of budget
            raise TimeoutError("slow edge")

        p = RetryPolicy(max_attempts=100, base_delay_s=0.5, max_delay_s=0.5,
                        deadline_s=1.0)
        with pytest.raises(TimeoutError):
            p.call(fn, rng=random.Random(3), sleep=clk.sleep, clock=clk)
        # far fewer than max_attempts: the deadline stopped the schedule,
        # and never by sleeping past it (give-up happens pre-sleep)
        assert len(calls) < 6
        assert clk.t <= 1.0 + 0.4  # last attempt's own cost may overshoot

    def test_zero_deadline_disables_cap(self):
        clk = FakeClock()
        calls = []

        def fn():
            calls.append(1)
            clk.t += 100.0
            raise ConnectionResetError()

        p = RetryPolicy(max_attempts=4, base_delay_s=0.01, deadline_s=0)
        with pytest.raises(ConnectionResetError):
            p.call(fn, rng=random.Random(4), sleep=clk.sleep, clock=clk)
        assert len(calls) == 4  # attempts, not elapsed time, bounded it


# --- CircuitBreaker --------------------------------------------------------


class TestCircuitBreaker:
    def test_full_transition_cycle(self):
        clk = FakeClock()
        edge = "unit.brk1"
        t_before = {
            to: metrics.breaker_transitions_total.value(edge, to)
            for to in ("open", "half-open", "closed")
        }
        b = CircuitBreaker(edge=edge, failure_threshold=2,
                           reset_timeout_s=5.0, clock=clk)
        assert b.state == "closed"
        assert b.allow()
        b.record_failure()
        assert b.state == "closed"  # below threshold
        b.record_failure()
        assert b.state == "open"
        assert metrics.breaker_state.value(edge) == 1
        assert not b.allow()  # cooldown not elapsed
        clk.t += 5.0
        assert b.allow()  # admitted as the half-open probe
        assert b.state == "half-open"
        assert metrics.breaker_state.value(edge) == 2
        b.record_success()
        assert b.state == "closed"
        assert metrics.breaker_state.value(edge) == 0
        for to, n in (("open", 1), ("half-open", 1), ("closed", 1)):
            assert (
                metrics.breaker_transitions_total.value(edge, to)
                - t_before[to] == n
            ), to

    def test_half_open_admits_single_probe(self):
        clk = FakeClock()
        b = CircuitBreaker(edge="unit.brk2", failure_threshold=1,
                           reset_timeout_s=1.0, clock=clk)
        b.record_failure()
        assert b.state == "open"
        clk.t += 1.0
        assert b.allow()       # the probe
        assert not b.allow()   # concurrent callers keep failing fast
        b.record_failure()     # probe failed → re-open, cooldown restarts
        assert b.state == "open"
        assert not b.allow()
        clk.t += 1.0
        assert b.allow()
        b.record_success()
        assert b.state == "closed"

    def test_policy_fails_fast_when_open(self):
        clk = FakeClock()
        b = CircuitBreaker(edge="unit.brk3", failure_threshold=1,
                           reset_timeout_s=10.0, clock=clk)
        calls = []

        def fn():
            calls.append(1)
            raise ConnectionRefusedError()

        p = RetryPolicy(max_attempts=1, deadline_s=0)
        with pytest.raises(ConnectionRefusedError):
            p.call(fn, edge="unit.brk3", breaker=b, sleep=clk.sleep, clock=clk)
        assert b.state == "open"
        # second call never reaches fn: microsecond fail-fast
        with pytest.raises(BreakerOpenError):
            p.call(fn, edge="unit.brk3", breaker=b, sleep=clk.sleep, clock=clk)
        assert len(calls) == 1

    def test_domain_errors_count_as_edge_success(self):
        # a 404 means the server ANSWERED: the edge is healthy and must
        # not trip, no matter how many domain errors a caller collects
        clk = FakeClock()
        b = CircuitBreaker(edge="unit.brk4", failure_threshold=1,
                           reset_timeout_s=1.0, clock=clk)
        p = RetryPolicy(max_attempts=1, deadline_s=0)

        def fn():
            raise ValueError("not found")

        for _ in range(5):
            with pytest.raises(ValueError):
                p.call(fn, edge="unit.brk4", breaker=b,
                       sleep=clk.sleep, clock=clk)
        assert b.state == "closed"
