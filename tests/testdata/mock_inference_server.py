"""Mock inference server — stand-in for the vLLM OpenAI server in tests.

Parity: reference test/testdata/vllm-mock/mock_server.py:1-37 (FastAPI
/health + /v1/models + /), rewritten on stdlib http.server so the test
image needs no extra dependencies. Accepts (and mostly ignores) the real
server's CLI flags so RuntimeConfig.build_args() drives it unchanged.
"""

import argparse
import http.server
import json


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="mock")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    # accepted for CLI parity, unused:
    p.add_argument("--tensor-parallel-size", default="1")
    p.add_argument("--gpu-memory-utilization", default="0.9")
    p.add_argument("--dtype", default="auto")
    p.add_argument("--max-model-len", default="0")
    args = p.parse_args()

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _json(self, obj, status=200):
            data = json.dumps(obj).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path == "/health":
                self._json({"status": "healthy"})  # mock_server.py:8-15
            elif self.path == "/v1/models":
                self._json(
                    {  # OpenAI-style list, mock_server.py:17-29
                        "object": "list",
                        "data": [
                            {"id": args.model, "object": "model", "owned_by": "mock"}
                        ],
                    }
                )
            elif self.path == "/":
                self._json({"message": "mock vllm server"})  # :31-33
            else:
                self.send_error(404)

    server = http.server.ThreadingHTTPServer((args.host, args.port), Handler)
    server.serve_forever()


if __name__ == "__main__":
    main()
