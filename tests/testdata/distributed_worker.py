"""Multi-process worker for the jax.distributed integration test.

Each OS process joins the process group via kubeinfer_tpu.distributed,
builds the global (jobs, nodes) mesh spanning both processes, and runs a
REAL sharded solve — the closest a single host gets to the multi-host
DCN topology (two processes, separate XLA clients, a cross-process
collective mesh).

Usage: distributed_worker.py <rank> <nprocs> <port>
"""

import sys

rank, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

from kubeinfer_tpu.distributed import (  # noqa: E402
    DistributedConfig,
    global_mesh,
    initialize,
)

assert initialize(DistributedConfig(f"127.0.0.1:{port}", rank, nprocs))

import jax  # noqa: E402
import numpy as np  # noqa: E402

assert jax.process_count() == nprocs, jax.process_count()
assert len(jax.devices()) == nprocs  # one cpu device per process

mesh = global_mesh(node_axis=1)
assert mesh.shape["jobs"] == nprocs

from kubeinfer_tpu.solver.problem import encode_problem_arrays  # noqa: E402
from kubeinfer_tpu.solver.sharded import solve_sharded  # noqa: E402

rng = np.random.default_rng(0)  # same seed everywhere: SPMD inputs agree
p = encode_problem_arrays(
    job_gpu=rng.integers(1, 4, 64).astype(np.float32),
    job_mem_gib=rng.integers(1, 8, 64).astype(np.float32),
    node_gpu_free=np.full(16, 8.0, np.float32),
    node_mem_free_gib=np.full(16, 64.0, np.float32),
    job_multiple=nprocs,
)
out = solve_sharded(p, mesh)
placed = int(out.placed)
assert placed > 0, "multi-process sharded solve placed nothing"
print(f"rank {rank}: placed {placed}", flush=True)
