"""Parity tests: Pallas flash attention vs the dense jnp path.

Runs the kernel in interpreter mode (works on the CPU test mesh); the
real-TPU path is exercised by bench.py's engine benchmark. Parity target:
model.attention (same inputs -> same outputs within dtype tolerance),
including GQA grouping, multi-tile accumulation, ragged masks, and
fully-masked (padding) rows.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from kubeinfer_tpu.inference.flash_attention import (
    attention_auto,
    flash_attention,
)
from kubeinfer_tpu.inference.model import attention as dense_attention


def _rand(key, B, T, S, n_heads, n_kv, D, dtype):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, T, n_heads, D), jnp.float32).astype(dtype)
    k = jax.random.normal(kk, (B, S, n_kv, D), jnp.float32).astype(dtype)
    v = jax.random.normal(kv, (B, S, n_kv, D), jnp.float32).astype(dtype)
    return q, k, v


class TestFlashParity:
    def _check(self, B, T, S, n_heads, n_kv, D, mask, dtype=jnp.float32,
               tile_t=8, tile_s=16, atol=2e-5):
        q, k, v = _rand(jax.random.PRNGKey(0), B, T, S, n_heads, n_kv, D,
                        dtype)
        want = dense_attention(q, k, v, mask)
        got = flash_attention(
            q, k, v, mask, tile_t=tile_t, tile_s=tile_s, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            atol=atol, rtol=1e-4,
        )

    def test_causal_multi_tile(self):
        # 4 query tiles x 4 kv tiles exercises the cross-tile recurrence
        T = S = 64
        mask = jnp.broadcast_to(
            jnp.tril(jnp.ones((T, S), bool))[None], (2, T, S)
        )
        self._check(2, T, S, 4, 4, 16, mask)

    def test_gqa_groups_fold(self):
        T, S = 16, 32
        mask = jnp.broadcast_to(
            jnp.tril(jnp.ones((T, S), bool), k=S - T)[None], (2, T, S)
        )
        self._check(2, T, S, 8, 2, 16, mask)

    def test_ragged_cache_mask(self):
        # prefill-chunk shape: T queries against a longer cache with
        # per-row valid lengths (the engine's actual mask pattern)
        B, T, S = 3, 8, 48
        lens = jnp.asarray([5, 48, 17])
        pos = jnp.arange(S)
        q_pos = 40 + jnp.arange(T)  # chunk offset 40
        mask = (pos[None, None, :] <= q_pos[None, :, None]) & (
            pos[None, None, :] < lens[:, None, None]
        )
        self._check(B, T, S, 4, 2, 8, jnp.broadcast_to(mask, (B, T, S)))

    def test_fully_masked_rows_match_dense(self):
        # rows with nothing attendable: dense softmax of a constant row
        # is uniform; flash must reproduce that (p == 1 everywhere)
        B, T, S = 1, 8, 16
        mask = jnp.zeros((B, T, S), bool)
        self._check(B, T, S, 2, 2, 8, mask)

    def test_bf16_inputs(self):
        T = S = 32
        mask = jnp.broadcast_to(
            jnp.tril(jnp.ones((T, S), bool))[None], (1, T, S)
        )
        self._check(1, T, S, 4, 2, 16, mask, dtype=jnp.bfloat16, atol=2e-2)

    def test_single_tile_equals_multi_tile(self):
        T = S = 32
        mask = jnp.broadcast_to(
            jnp.tril(jnp.ones((T, S), bool))[None], (1, T, S)
        )
        q, k, v = _rand(jax.random.PRNGKey(1), 1, T, S, 4, 4, 8,
                        jnp.float32)
        one = flash_attention(q, k, v, mask, tile_t=32, tile_s=32,
                              interpret=True)
        many = flash_attention(q, k, v, mask, tile_t=8, tile_s=8,
                               interpret=True)
        np.testing.assert_allclose(
            np.asarray(one), np.asarray(many), atol=2e-5, rtol=1e-4
        )

    def test_auto_falls_back_off_tpu(self):
        # CPU test env: attention_auto must route to the dense path and
        # still be exact
        T, S = 8, 16
        mask = jnp.broadcast_to(
            jnp.tril(jnp.ones((T, S), bool), k=S - T)[None], (1, T, S)
        )
        q, k, v = _rand(jax.random.PRNGKey(2), 1, T, S, 2, 2, 8,
                        jnp.float32)
        np.testing.assert_allclose(
            np.asarray(attention_auto(q, k, v, mask)),
            np.asarray(dense_attention(q, k, v, mask)),
        )

    def test_rejects_unaligned(self):
        with pytest.raises(ValueError, match="divisible"):
            q, k, v = _rand(jax.random.PRNGKey(3), 1, 24, 24, 2, 2, 8,
                            jnp.float32)
            flash_attention(q, k, v, jnp.ones((1, 24, 24), bool),
                            tile_t=16, tile_s=16, interpret=True)


class TestRaggedKernel:
    """flash_attention_ragged derives the engine's prefill mask from
    (chunk offset, row lengths) in-kernel; parity target is the dense
    path fed the equivalently constructed bool mask."""

    def _mask(self, B, T, S, c0, lens):
        pos = jnp.arange(S)
        q_pos = c0 + jnp.arange(T)
        m = (pos[None, None, :] <= q_pos[None, :, None]) & (
            pos[None, None, :] < jnp.asarray(lens)[:, None, None]
        )
        return jnp.broadcast_to(m, (B, T, S))

    @pytest.mark.parametrize("c0", [0, 8, 40])
    def test_matches_dense_with_equivalent_mask(self, c0):
        from kubeinfer_tpu.inference.flash_attention import (
            flash_attention_ragged,
        )

        B, T, S = 3, 8, 48
        lens = [5, 48, 17]
        q, k, v = _rand(jax.random.PRNGKey(4), B, T, S, 4, 2, 8,
                        jnp.float32)
        want = dense_attention(q, k, v, self._mask(B, T, S, c0, lens))
        got = flash_attention_ragged(
            q, k, v, jnp.int32(c0), jnp.asarray(lens, jnp.int32),
            tile_t=8, tile_s=16, interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4
        )

    def test_multi_tile_gqa(self):
        from kubeinfer_tpu.inference.flash_attention import (
            flash_attention_ragged,
        )

        B, T, S = 2, 32, 64
        lens = [64, 20]
        q, k, v = _rand(jax.random.PRNGKey(5), B, T, S, 8, 2, 16,
                        jnp.float32)
        want = dense_attention(q, k, v, self._mask(B, T, S, 16, lens))
        got = flash_attention_ragged(
            q, k, v, jnp.int32(16), jnp.asarray(lens, jnp.int32),
            tile_t=8, tile_s=16, interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4
        )

    def test_engine_prefill_unchanged_on_cpu(self):
        # CPU: flash_available is False, so generate must behave exactly
        # as before the ragged wiring (the dense path is untouched)
        from kubeinfer_tpu.inference import PRESETS, init_params
        from kubeinfer_tpu.inference.engine import Engine

        params = init_params(PRESETS["tiny"], jax.random.PRNGKey(0))
        out = Engine(params, PRESETS["tiny"]).generate(
            [[1, 2, 3, 4, 5]], max_new_tokens=4
        )
        assert out.tokens.shape == (1, 4)

    def test_engine_flash_branch_parity_via_interpret(self, monkeypatch):
        # The engine's use_flash branch (closure-captured scan carry c0,
        # prompt_len as row_lens) is TPU-only in production; route it
        # through the interpreted ragged kernel on CPU and pin generate()
        # token-equality against the dense path (r2 review: this wiring
        # was otherwise unreachable by the suite).
        import functools

        import kubeinfer_tpu.inference.engine as eng_mod
        from kubeinfer_tpu.inference import PRESETS, init_params
        from kubeinfer_tpu.inference.engine import Engine
        from kubeinfer_tpu.inference.flash_attention import (
            flash_attention_ragged,
        )

        params = init_params(PRESETS["tiny"], jax.random.PRNGKey(0))
        prompts = [[1, 2, 3], [4, 5, 6, 7, 8, 9, 10, 11]]
        ref = Engine(params, PRESETS["tiny"]).generate(
            prompts, max_new_tokens=6
        )

        monkeypatch.setattr(eng_mod, "flash_available", lambda *a: True)
        monkeypatch.setattr(
            eng_mod, "flash_attention_ragged",
            functools.partial(
                flash_attention_ragged, tile_t=8, tile_s=16, interpret=True
            ),
        )
        eng_mod._generate_jit._clear_cache()
        try:
            got = Engine(params, PRESETS["tiny"]).generate(
                prompts, max_new_tokens=6
            )
        finally:
            eng_mod._generate_jit._clear_cache()  # drop patched traces
        np.testing.assert_array_equal(got.tokens, ref.tokens)
        np.testing.assert_array_equal(got.lengths, ref.lengths)


class TestCausalAuto:
    """The no-cache causal path's in-kernel mask (r2 verdict item 8):
    flash_attention_ragged at q_offset=0, row_lens=S must equal both the
    dense causal reference and the relegated mask-tensor kernel."""

    def test_causal_kernel_matches_dense(self):
        import numpy as np
        from kubeinfer_tpu.inference.flash_attention import (
            flash_attention_ragged,
        )
        from kubeinfer_tpu.inference.model import attention, causal_mask

        rng = np.random.default_rng(3)
        B, T, H, KV, D = 2, 256, 4, 2, 64
        q = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, T, KV, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, T, KV, D)), jnp.float32)
        mask = jnp.broadcast_to(causal_mask(T)[None], (B, T, T))
        ref = attention(q, k, v, mask)
        got = flash_attention_ragged(
            q, k, v, 0, jnp.full((B,), T, jnp.int32),
            tile_t=128, tile_s=128, interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_forward_no_mask_unchanged_numerics(self):
        """model.forward's no-mask path now routes through
        causal_attention_auto — on CPU (flash unavailable) that is the
        dense path bit-for-bit."""
        import numpy as np
        from kubeinfer_tpu.inference import PRESETS, init_params
        from kubeinfer_tpu.inference.model import (
            attention,
            causal_mask,
            forward,
        )

        cfg = PRESETS["tiny"]
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16)))
        auto_logits, _ = forward(params, toks, cfg)
        B, T = toks.shape
        explicit_mask = jnp.broadcast_to(causal_mask(T)[None], (B, T, T))
        ref_logits, _ = forward(
            params, toks, cfg, attn_mask=explicit_mask, attn_fn=attention
        )
        np.testing.assert_array_equal(
            np.asarray(auto_logits), np.asarray(ref_logits)
        )


class TestFlashBackward:
    """The recompute-based custom_vjp (r3 verdict item 6): gradients of
    the flash path must match the dense path's at tolerance, across
    multi-tile grids, GQA grouping, ragged lengths, and bf16 inputs."""

    def _grads(self, B, T, n_heads, n_kv, D, lens, dtype=jnp.float32,
               tile_t=8, tile_s=16):
        import kubeinfer_tpu.inference.flash_attention as fa

        q, k, v = _rand(jax.random.PRNGKey(3), B, T, T, n_heads, n_kv, D,
                        dtype)
        row_lens = jnp.asarray(lens, jnp.int32)
        w = jax.random.normal(
            jax.random.PRNGKey(7), (B, T, n_heads, D), jnp.float32
        )

        t_pos = jnp.arange(T)
        mask = (
            (t_pos[None, :, None] >= t_pos[None, None, :])
            & (t_pos[None, None, :] < row_lens[:, None, None])
        )

        def loss_dense(q, k, v):
            o = dense_attention(q, k, v, mask)
            return jnp.sum(o.astype(jnp.float32) * w)

        mp = pytest.MonkeyPatch()
        mp.setattr(fa, "TILE_T", tile_t)
        mp.setattr(fa, "TILE_S", tile_s)
        try:
            def loss_flash(q, k, v):
                o = fa.flash_attention_causal_diff(
                    True, q, k, v, 0, row_lens
                )
                return jnp.sum(o.astype(jnp.float32) * w)

            gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
            gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        finally:
            mp.undo()
        return gd, gf

    def _assert_close(self, gd, gf, atol):
        for want, got, name in zip(gd, gf, "qkv"):
            np.testing.assert_allclose(
                np.asarray(got, np.float32), np.asarray(want, np.float32),
                atol=atol, rtol=5e-3, err_msg=f"d{name}",
            )

    def test_grad_parity_multi_tile(self):
        gd, gf = self._grads(2, 32, 4, 4, 16, [32, 20])
        self._assert_close(gd, gf, 2e-4)

    def test_grad_parity_gqa(self):
        gd, gf = self._grads(1, 32, 4, 2, 16, [25])
        self._assert_close(gd, gf, 2e-4)

    def test_grad_parity_bf16(self):
        gd, gf = self._grads(1, 32, 2, 2, 16, [32], dtype=jnp.bfloat16)
        self._assert_close(gd, gf, 5e-2)

    def test_grad_zero_for_empty_rows(self):
        """row_len == 0 rows must contribute NO gradient. The naive
        recompute would give p == 1 per slot there (s and lse both
        saturate at -1e30 in f32, so exp(s - lse) == 1); _recompute_p
        gates those rows to 0. Note this deliberately diverges from the
        dense path's dv, which leaks a uniform 1/S spread into v for
        fully-masked rows (softmax-of-constant artifact) — zero is the
        right semantics for padding rows. Non-empty rows still match
        dense."""
        gd, gf = self._grads(2, 32, 4, 4, 16, [20, 0])
        for got, name in zip(gf, "qkv"):
            np.testing.assert_array_equal(
                np.asarray(got, np.float32)[1],
                np.zeros_like(np.asarray(got, np.float32)[1]),
                err_msg=f"d{name} row_len=0",
            )
        # row 0 (live) still matches dense; dense dv row 1 carries the
        # 1/S leak so only q/k rows and the live dv row are compared
        for want, got, name in zip(gd, gf, "qkv"):
            np.testing.assert_allclose(
                np.asarray(got, np.float32)[0],
                np.asarray(want, np.float32)[0],
                atol=2e-4, rtol=5e-3, err_msg=f"d{name} live row",
            )

    def test_primal_value_unchanged(self):
        """The custom_vjp primal must equal the plain ragged kernel
        bit-for-bit (custom_vjp contract: fwd reproduces the primal)."""
        import kubeinfer_tpu.inference.flash_attention as fa

        q, k, v = _rand(
            jax.random.PRNGKey(1), 1, 16, 16, 2, 2, 16, jnp.float32
        )
        lens = jnp.asarray([16], jnp.int32)
        a = fa.flash_attention_ragged(
            q, k, v, 0, lens, tile_t=8, tile_s=16, interpret=True
        )
        mp = pytest.MonkeyPatch()
        mp.setattr(fa, "TILE_T", 8)
        mp.setattr(fa, "TILE_S", 16)
        try:
            b = fa.flash_attention_causal_diff(True, q, k, v, 0, lens)
            # the fwd-with-lse variant's primal output (what callers see
            # under differentiation) must also be bit-identical
            c, _ = jax.vjp(
                lambda q, k, v: fa.flash_attention_causal_diff(
                    True, q, k, v, 0, lens
                ),
                q, k, v,
            )
        finally:
            mp.undo()
        assert np.array_equal(np.asarray(a), np.asarray(b))
        assert np.array_equal(np.asarray(a), np.asarray(c))

    def test_train_loss_differentiates_with_flash(self):
        """causal_lm_loss's default binding differentiates end to end
        when the flash path engages (forced here via interpret-mode
        attn_fn); loss and grads match the dense-pinned variant."""
        from kubeinfer_tpu.inference import PRESETS, init_params
        from kubeinfer_tpu.inference.train import causal_lm_loss
        import kubeinfer_tpu.inference.flash_attention as fa

        cfg = PRESETS["tiny"]
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(
            rng.integers(1, cfg.vocab_size, (1, 17)), jnp.int32
        )

        mp = pytest.MonkeyPatch()
        mp.setattr(fa, "TILE_T", 8)
        mp.setattr(fa, "TILE_S", 16)
        try:
            def flash_fn(q, k, v, mask):
                B, S = q.shape[0], k.shape[1]
                return fa.flash_attention_causal_diff(
                    True, q, k, v, 0, jnp.full((B,), S, jnp.int32)
                )

            lf, gf = jax.value_and_grad(causal_lm_loss)(
                params, tokens, cfg, flash_fn
            )
            ld, gd = jax.value_and_grad(causal_lm_loss)(
                params, tokens, cfg, None
            )
        finally:
            mp.undo()
        np.testing.assert_allclose(float(lf), float(ld), rtol=1e-5)
        flat_f = jax.tree.leaves(gf)
        flat_d = jax.tree.leaves(gd)
        for a, b in zip(flat_f, flat_d):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                atol=3e-4, rtol=5e-3,
            )


class TestDecodeKernel:
    """The batched decode kernel (T == 1, per-row live lengths) vs its
    jnp twin: BIT-identical (np.array_equal) per the repo's kernel/twin
    invariant — both run _fold_tile_math over the same tile sweep — and
    the twin vs the dense path at dtype tolerance. Edge lengths cover
    a row at offset 0 (length 1), a row at the full cache, and the
    degenerate all-masked (length 0) row whose defined output is the
    uniform average over the padded cache."""

    def _decode_rand(self, key, B, S, n_heads, n_kv, D, dtype):
        return _rand(key, B, 1, S, n_heads, n_kv, D, dtype)

    def _check(self, B, S, n_heads, n_kv, D, lens, dtype=jnp.float32,
               tile_s=16, dense_atol=2e-5, dense_rtol=1e-4):
        import kubeinfer_tpu.inference.flash_attention as fa

        q, k, v = self._decode_rand(
            jax.random.PRNGKey(11), B, S, n_heads, n_kv, D, dtype
        )
        lengths = jnp.asarray(lens, jnp.int32)
        got = fa.decode_attention(
            q, k, v, lengths, tile_s=tile_s, interpret=True
        )
        twin = fa.decode_attention_jnp(q, k, v, lengths, tile_s=tile_s)
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(twin),
            err_msg="kernel/twin bit-identity",
        )
        mask = (
            jnp.arange(S)[None, None, :] < lengths[:, None, None]
        )
        want = dense_attention(q, k, v, jnp.broadcast_to(mask, (B, 1, S)))
        np.testing.assert_allclose(
            np.asarray(twin, np.float32), np.asarray(want, np.float32),
            atol=dense_atol, rtol=dense_rtol,
        )

    @pytest.mark.parametrize("n_heads,n_kv", [(4, 4), (8, 2), (8, 1)])
    def test_gqa_ratios_mixed_lengths(self, n_heads, n_kv):
        # per-row lengths straddling tile boundaries: mid-tile, exactly
        # one tile, full cache, length 1
        self._check(4, 48, n_heads, n_kv, 16, [17, 16, 48, 1])

    @pytest.mark.parametrize("n_heads,n_kv", [(4, 4), (8, 2)])
    def test_bf16(self, n_heads, n_kv):
        self._check(
            3, 48, n_heads, n_kv, 16, [5, 48, 33], dtype=jnp.bfloat16,
            dense_atol=3e-2, dense_rtol=1e-1,
        )

    def test_edge_lengths(self):
        # offset-0 row (one live slot), full-cache row, zero-length row
        self._check(3, 32, 4, 2, 8, [1, 32, 0])

    def test_all_done_batch(self):
        # every row degenerate (the all-slots-retired batcher shape):
        # the kernel must keep the zero-length rows' tiles live and
        # reproduce the dense uniform average bit-for-bit vs the twin
        self._check(3, 32, 4, 2, 8, [0, 0, 0])

    def test_single_tile_equals_multi_tile(self):
        import kubeinfer_tpu.inference.flash_attention as fa

        q, k, v = self._decode_rand(
            jax.random.PRNGKey(12), 3, 32, 4, 2, 8, jnp.float32
        )
        lengths = jnp.asarray([7, 32, 0], jnp.int32)
        one = fa.decode_attention(
            q, k, v, lengths, tile_s=32, interpret=True
        )
        many = fa.decode_attention(
            q, k, v, lengths, tile_s=8, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(one), np.asarray(many), atol=2e-5, rtol=1e-4
        )

    def test_rejects_multi_token_and_unaligned(self):
        import kubeinfer_tpu.inference.flash_attention as fa

        q, k, v = _rand(
            jax.random.PRNGKey(13), 1, 8, 16, 2, 2, 8, jnp.float32
        )
        with pytest.raises(ValueError, match="T == 1"):
            fa.decode_attention(
                q, k, v, jnp.asarray([8], jnp.int32), interpret=True
            )
        q1 = q[:, :1]
        with pytest.raises(ValueError, match="divisible"):
            fa.decode_attention(
                q1, k, v, jnp.asarray([8], jnp.int32), tile_s=12,
                interpret=True,
            )

    def test_auto_falls_back_off_tpu(self):
        # CPU test env: decode_attention_auto must take the dense path
        import kubeinfer_tpu.inference.flash_attention as fa

        q, k, v = self._decode_rand(
            jax.random.PRNGKey(14), 2, 16, 2, 2, 8, jnp.float32
        )
        lengths = jnp.asarray([3, 16], jnp.int32)
        mask = jnp.broadcast_to(
            jnp.arange(16)[None, None, :] < lengths[:, None, None],
            (2, 1, 16),
        )
        np.testing.assert_array_equal(
            np.asarray(fa.decode_attention_auto(q, k, v, lengths, mask)),
            np.asarray(dense_attention(q, k, v, mask)),
        )

    def test_engine_decode_route_token_parity(self, monkeypatch):
        # Route the engine's decode steps through the interpreted kernel
        # (production wiring is TPU-only) and pin generate() token
        # equality against the unpatched dense route — same harness as
        # the prefill flash-branch test above.
        import functools

        import kubeinfer_tpu.inference.engine as eng_mod
        import kubeinfer_tpu.inference.flash_attention as fa
        import kubeinfer_tpu.inference.stepper as stepper
        from kubeinfer_tpu.inference import PRESETS, init_params
        from kubeinfer_tpu.inference.engine import Engine

        params = init_params(PRESETS["tiny"], jax.random.PRNGKey(0))
        prompts = [[1, 2, 3], [4, 5, 6, 7, 8, 9, 10, 11], [9]]
        ref = Engine(params, PRESETS["tiny"]).generate(
            prompts, max_new_tokens=6
        )

        kern = functools.partial(
            fa.decode_attention, tile_s=8, interpret=True
        )
        # the decode route resolves its attention in stepper (the one
        # module all three decode paths share), not engine
        monkeypatch.setattr(
            stepper, "decode_attention_auto",
            lambda q, k, v, lengths, mask: kern(q, k, v, lengths),
        )
        eng_mod._generate_jit._clear_cache()
        try:
            got = Engine(params, PRESETS["tiny"]).generate(
                prompts, max_new_tokens=6
            )
        finally:
            eng_mod._generate_jit._clear_cache()  # drop patched traces
        np.testing.assert_array_equal(got.tokens, ref.tokens)
        np.testing.assert_array_equal(got.lengths, ref.lengths)


class TestBlockDecodeKernel:
    """Block-table decode (paged KV) vs its jnp twin: BIT-identical per
    the kernel/twin invariant — both resolve every KV tile through the
    same scalar-prefetched block table and fold with _fold_tile_math.
    Pools are junk-filled outside the scattered logical blocks and the
    tables deliberately non-contiguous, so any read that escapes the
    table (or depends on dead table entries) breaks parity loudly."""

    def _paged(self, key, B, max_blocks, block_size, n_heads, n_kv, D,
               lens, dtype=jnp.float32, extra_blocks=3):
        """Scatter a logical [B, S] KV into a junk-initialised pool at
        permuted (non-contiguous, interleaved-across-rows) block ids.
        Returns the paged operands plus the gathered dense KV."""
        import kubeinfer_tpu.inference.flash_attention as fa

        S = max_blocks * block_size
        q, k, v = _rand(key, B, 1, S, n_heads, n_kv, D, dtype)
        num_blocks = 1 + B * max_blocks + extra_blocks
        jk, jv = jax.random.split(jax.random.fold_in(key, 7))
        kp = jax.random.normal(
            jk, (num_blocks, block_size, n_kv, D)
        ).astype(dtype)
        vp = jax.random.normal(
            jv, (num_blocks, block_size, n_kv, D)
        ).astype(dtype)
        rng = np.random.default_rng(17)
        perm = rng.permutation(np.arange(1, num_blocks))
        tables = perm[: B * max_blocks].reshape(B, max_blocks)
        tables = np.ascontiguousarray(tables, np.int32)
        kp = kp.at[tables.reshape(-1)].set(
            k.reshape(B * max_blocks, block_size, n_kv, D)
        )
        vp = vp.at[tables.reshape(-1)].set(
            v.reshape(B * max_blocks, block_size, n_kv, D)
        )
        # dead entries (beyond each row's live blocks) point at the
        # null block, as the engine pads them — output must not care
        lens = np.asarray(lens, np.int64)
        for b in range(B):
            live = -(-int(lens[b]) // block_size)
            tables[b, live:] = 0
        tables = jnp.asarray(tables)
        lengths = jnp.asarray(lens, jnp.int32)
        kg = fa.gather_block_kv(kp, tables)
        vg = fa.gather_block_kv(vp, tables)
        return q, kp, vp, tables, lengths, kg, vg

    def _check(self, B, max_blocks, block_size, n_heads, n_kv, D, lens,
               dtype=jnp.float32, dense_atol=2e-5, dense_rtol=1e-4):
        import kubeinfer_tpu.inference.flash_attention as fa

        q, kp, vp, tables, lengths, kg, vg = self._paged(
            jax.random.PRNGKey(21), B, max_blocks, block_size, n_heads,
            n_kv, D, lens, dtype,
        )
        got = fa.decode_attention_blocks(
            q, kp, vp, tables, lengths, interpret=True
        )
        twin = fa.decode_attention_blocks_jnp(q, kp, vp, tables, lengths)
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(twin),
            err_msg="block kernel/twin bit-identity",
        )
        S = max_blocks * block_size
        mask = jnp.broadcast_to(
            jnp.arange(S)[None, None, :] < lengths[:, None, None],
            (B, 1, S),
        )
        want = dense_attention(q, kg, vg, mask)
        np.testing.assert_allclose(
            np.asarray(twin, np.float32), np.asarray(want, np.float32),
            atol=dense_atol, rtol=dense_rtol,
        )

    @pytest.mark.slow
    @pytest.mark.parametrize("n_heads,n_kv", [(4, 4), (8, 2), (8, 1)])
    def test_gqa_ratios_mixed_lengths(self, n_heads, n_kv):
        # lengths straddle block boundaries: mid-block, exactly one
        # block, full table, single token
        self._check(4, 3, 16, n_heads, n_kv, 16, [17, 16, 48, 1])

    @pytest.mark.slow
    def test_bf16(self):
        self._check(
            3, 3, 16, 8, 2, 16, [5, 48, 33], dtype=jnp.bfloat16,
            dense_atol=3e-2, dense_rtol=1e-1,
        )

    def test_zero_length_rows(self):
        # retired-slot rows (length 0, table all null) must stay dense
        # over the junk they point at — defined output, never NaN —
        # alongside live rows
        self._check(3, 2, 16, 4, 2, 8, [0, 32, 0])

    def test_twin_matches_linear_twin(self):
        # the block twin over a gathered-contiguous pool must equal the
        # linear decode twin with tile_s == block_size bit-for-bit:
        # same tile sweep, same fold math, only the addressing differs
        import kubeinfer_tpu.inference.flash_attention as fa

        q, kp, vp, tables, lengths, kg, vg = self._paged(
            jax.random.PRNGKey(22), 3, 3, 16, 8, 2, 16, [17, 48, 0]
        )
        twin = fa.decode_attention_blocks_jnp(q, kp, vp, tables, lengths)
        linear = fa.decode_attention_jnp(q, kg, vg, lengths, tile_s=16)
        np.testing.assert_array_equal(
            np.asarray(twin), np.asarray(linear),
            err_msg="block twin vs linear twin bit-identity",
        )

    def test_shared_prefix_blocks(self):
        # radix reuse aliases one physical block into several rows'
        # tables; the kernel only ever reads KV, so aliased tables must
        # behave exactly like their gathered-dense expansion
        import kubeinfer_tpu.inference.flash_attention as fa

        B, bs, n_kv, D = 3, 16, 2, 8
        q, _, _ = _rand(
            jax.random.PRNGKey(23), B, 1, 2 * bs, 4, n_kv, D,
            jnp.float32,
        )
        jk, jv = jax.random.split(jax.random.PRNGKey(24))
        kp = jax.random.normal(jk, (6, bs, n_kv, D))
        vp = jax.random.normal(jv, (6, bs, n_kv, D))
        tables = jnp.asarray(
            [[5, 2], [5, 4], [5, 1]], jnp.int32  # block 5 shared 3-ways
        )
        lengths = jnp.asarray([32, 20, 16], jnp.int32)
        got = fa.decode_attention_blocks(
            q, kp, vp, tables, lengths, interpret=True
        )
        twin = fa.decode_attention_blocks_jnp(q, kp, vp, tables, lengths)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(twin))
        mask = jnp.broadcast_to(
            jnp.arange(2 * bs)[None, None, :] < lengths[:, None, None],
            (B, 1, 2 * bs),
        )
        want = dense_attention(
            q, fa.gather_block_kv(kp, tables),
            fa.gather_block_kv(vp, tables), mask,
        )
        np.testing.assert_allclose(
            np.asarray(twin), np.asarray(want), atol=2e-5, rtol=1e-4
        )

    def test_auto_falls_back_off_tpu(self):
        # CPU test env: blocks_auto must take the gathered dense path
        import kubeinfer_tpu.inference.flash_attention as fa

        q, kp, vp, tables, lengths, kg, vg = self._paged(
            jax.random.PRNGKey(25), 2, 2, 16, 4, 2, 8, [9, 32]
        )
        mask = jnp.broadcast_to(
            jnp.arange(32)[None, None, :] < lengths[:, None, None],
            (2, 1, 32),
        )
        np.testing.assert_array_equal(
            np.asarray(
                fa.decode_attention_blocks_auto(
                    q, kp, vp, tables, lengths, mask
                )
            ),
            np.asarray(dense_attention(q, kg, vg, mask)),
        )


class TestKQueryBlockDecode:
    """Speculative verify window: the block kernel's T > 1 path vs its
    jnp twin (bit-identical) and the dense reference under the window's
    causal rule (query t admits s <= lengths[b] - T + t). Fixtures keep
    the TestBlockDecodeKernel hostility — junk-filled pools, permuted
    non-contiguous tables, null-padded dead entries — plus the
    verify-specific edges: rows shorter than the window and retired
    rows (length 0, all-null table) riding the same dispatch."""

    def _paged(self, key, B, T, max_blocks, block_size, n_heads, n_kv,
               D, lens, dtype=jnp.float32):
        import kubeinfer_tpu.inference.flash_attention as fa

        S = max_blocks * block_size
        q, k, v = _rand(key, B, T, S, n_heads, n_kv, D, dtype)
        num_blocks = 1 + B * max_blocks + 3
        jk, jv = jax.random.split(jax.random.fold_in(key, 7))
        kp = jax.random.normal(
            jk, (num_blocks, block_size, n_kv, D)
        ).astype(dtype)
        vp = jax.random.normal(
            jv, (num_blocks, block_size, n_kv, D)
        ).astype(dtype)
        rng = np.random.default_rng(29)
        perm = rng.permutation(np.arange(1, num_blocks))
        tables = perm[: B * max_blocks].reshape(B, max_blocks)
        tables = np.ascontiguousarray(tables, np.int32)
        kp = kp.at[tables.reshape(-1)].set(
            k.reshape(B * max_blocks, block_size, n_kv, D)
        )
        vp = vp.at[tables.reshape(-1)].set(
            v.reshape(B * max_blocks, block_size, n_kv, D)
        )
        lens = np.asarray(lens, np.int64)
        for b in range(B):
            live = -(-int(lens[b]) // block_size)
            tables[b, live:] = 0
        tables = jnp.asarray(tables)
        lengths = jnp.asarray(lens, jnp.int32)
        kg = fa.gather_block_kv(kp, tables)
        vg = fa.gather_block_kv(vp, tables)
        return q, kp, vp, tables, lengths, kg, vg

    def _window_mask(self, lengths, T, S):
        q_pos = lengths[:, None] - T + jnp.arange(T, dtype=jnp.int32)
        return (
            jnp.arange(S, dtype=jnp.int32)[None, None, :]
            <= q_pos[:, :, None]
        )

    def _check(self, B, T, max_blocks, block_size, n_heads, n_kv, D,
               lens, dtype=jnp.float32, dense=True, dense_atol=2e-5,
               dense_rtol=1e-4, seed=31):
        import kubeinfer_tpu.inference.flash_attention as fa

        q, kp, vp, tables, lengths, kg, vg = self._paged(
            jax.random.PRNGKey(seed), B, T, max_blocks, block_size,
            n_heads, n_kv, D, lens, dtype,
        )
        got = fa.decode_attention_blocks(
            q, kp, vp, tables, lengths, interpret=True
        )
        twin = fa.decode_attention_blocks_jnp(q, kp, vp, tables, lengths)
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(twin),
            err_msg="K-query block kernel/twin bit-identity",
        )
        assert np.isfinite(np.asarray(twin, np.float32)).all()
        if dense:
            S = max_blocks * block_size
            want = dense_attention(
                q, kg, vg, self._window_mask(lengths, T, S)
            )
            np.testing.assert_allclose(
                np.asarray(twin, np.float32),
                np.asarray(want, np.float32),
                atol=dense_atol, rtol=dense_rtol,
            )

    def test_window_smoke(self):
        # T=2 window, lengths straddling block boundaries — the
        # un-slow sentinel for the sweep below
        self._check(3, 2, 2, 16, 4, 2, 8, [17, 32, 2])

    @pytest.mark.slow
    @pytest.mark.parametrize("n_heads,n_kv", [(4, 4), (8, 2), (8, 1)])
    @pytest.mark.parametrize("T", [2, 5])
    def test_gqa_ratios(self, n_heads, n_kv, T):
        # window end mid-block, at a block edge, at the table's end,
        # and the minimum live row (offset 0: length == T)
        self._check(4, T, 3, 16, n_heads, n_kv, 16, [17, 32, 48, T])

    @pytest.mark.slow
    def test_bf16(self):
        self._check(
            3, 3, 3, 16, 8, 2, 16, [19, 48, 3], dtype=jnp.bfloat16,
            dense_atol=3e-2, dense_rtol=1e-1,
        )

    def test_short_and_zero_rows(self):
        # rows the engine never produces but the fused dispatch must
        # survive: length 0 (retired slot, all-null table) and
        # 0 < length < T (every query below the window floor fully
        # masked) — twin bit-identity and finite output are the
        # contract; the dense reference has no defined answer for a
        # fully-masked query row, so it sits this one out
        self._check(3, 4, 2, 16, 4, 2, 8, [0, 2, 30], dense=False)

    def test_reduces_to_single_query(self):
        # the T=1 window through the generalized path must stay
        # bit-identical to the twin on the decode shapes the engine
        # ran before the verify path existed (pen s <= rl - 1 is the
        # old s < rl)
        self._check(3, 1, 2, 16, 4, 2, 8, [9, 32, 0])

    def test_auto_routes_window_to_dense_on_cpu(self):
        # CPU test env: the auto router's gather+dense branch under
        # the window mask must agree with the twin (same live-set
        # contract the T=1 router already keeps)
        import kubeinfer_tpu.inference.flash_attention as fa

        q, kp, vp, tables, lengths, kg, vg = self._paged(
            jax.random.PRNGKey(37), 2, 3, 2, 16, 4, 2, 8, [19, 32]
        )
        mask = self._window_mask(lengths, 3, 32)
        np.testing.assert_allclose(
            np.asarray(
                fa.decode_attention_blocks_auto(
                    q, kp, vp, tables, lengths, mask
                ), np.float32,
            ),
            np.asarray(
                fa.decode_attention_blocks_jnp(
                    q, kp, vp, tables, lengths
                ), np.float32,
            ),
            atol=2e-5, rtol=1e-4,
        )
