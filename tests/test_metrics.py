"""Metrics registry: collector semantics + Prometheus text exposition."""

import pytest

from kubeinfer_tpu.metrics.registry import Counter, Gauge, Histogram, Registry


class TestCounter:
    def test_inc_and_labels(self):
        r = Registry()
        c = Counter("t_total", "help", labels=("result",), registry=r)
        c.inc("ok")
        c.inc("ok", by=2)
        c.inc("err")
        assert c.value("ok") == 3
        assert c.value("err") == 1
        assert c.value("missing") == 0

    def test_label_arity_enforced(self):
        c = Counter("t2_total", "h", labels=("a", "b"), registry=None)
        with pytest.raises(ValueError):
            c.inc("only-one")


class TestGauge:
    def test_set_and_delete(self):
        g = Gauge("t_gauge", "h", labels=("ns", "name"), registry=None)
        g.set("default", "svc", 3)
        assert g.value("default", "svc") == 3
        g.delete("default", "svc")
        assert g.value("default", "svc") == 0

    def test_unlabeled_set(self):
        g = Gauge("t_g2", "h", registry=None)
        g.set(7)
        assert g.value() == 7


class TestHistogram:
    def test_cumulative_buckets(self):
        h = Histogram("t_seconds", "h", buckets=[0.1, 1.0, 10.0], registry=None)
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        h.observe(50.0)
        text = h.render()
        assert 'le="0.1"} 1' in text
        assert 'le="1"} 2' in text
        assert 'le="10"} 3' in text
        assert 'le="+Inf"} 4' in text
        assert h.count() == 4
        assert h.sum() == pytest.approx(55.55)

    def test_labeled_histogram(self):
        h = Histogram("t_s2", "h", buckets=[1], labels=("policy",), registry=None)
        h.observe("jax-greedy", 0.5)
        h.observe("jax-greedy", 2.0)
        assert h.count("jax-greedy") == 2
        assert 'policy="jax-greedy",le="+Inf"} 2' in h.render()


class TestRegistry:
    def test_render_and_reset(self):
        r = Registry()
        c = Counter("x_total", "counts x", registry=r)
        c.inc()
        text = r.render()
        assert "# HELP x_total counts x" in text
        assert "# TYPE x_total counter" in text
        assert "x_total 1" in text
        r.reset()
        assert "x_total 1" not in r.render()
