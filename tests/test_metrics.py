"""Metrics registry: collector semantics + Prometheus text exposition."""

import pytest

from kubeinfer_tpu.metrics.registry import Counter, Gauge, Histogram, Registry


class TestCounter:
    def test_inc_and_labels(self):
        r = Registry()
        c = Counter("t_total", "help", labels=("result",), registry=r)
        c.inc("ok")
        c.inc("ok", by=2)
        c.inc("err")
        assert c.value("ok") == 3
        assert c.value("err") == 1
        assert c.value("missing") == 0

    def test_label_arity_enforced(self):
        c = Counter("t2_total", "h", labels=("a", "b"), registry=None)
        with pytest.raises(ValueError):
            c.inc("only-one")


class TestGauge:
    def test_set_and_delete(self):
        g = Gauge("t_gauge", "h", labels=("ns", "name"), registry=None)
        g.set("default", "svc", 3)
        assert g.value("default", "svc") == 3
        g.delete("default", "svc")
        assert g.value("default", "svc") == 0

    def test_unlabeled_set(self):
        g = Gauge("t_g2", "h", registry=None)
        g.set(7)
        assert g.value() == 7


class TestHistogram:
    def test_cumulative_buckets(self):
        h = Histogram("t_seconds", "h", buckets=[0.1, 1.0, 10.0], registry=None)
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        h.observe(50.0)
        text = h.render()
        assert 'le="0.1"} 1' in text
        assert 'le="1"} 2' in text
        assert 'le="10"} 3' in text
        assert 'le="+Inf"} 4' in text
        assert h.count() == 4
        assert h.sum() == pytest.approx(55.55)

    def test_labeled_histogram(self):
        h = Histogram("t_s2", "h", buckets=[1], labels=("policy",), registry=None)
        h.observe("jax-greedy", 0.5)
        h.observe("jax-greedy", 2.0)
        assert h.count("jax-greedy") == 2
        assert 'policy="jax-greedy",le="+Inf"} 2' in h.render()


class TestRegistry:
    def test_render_and_reset(self):
        r = Registry()
        c = Counter("x_total", "counts x", registry=r)
        c.inc()
        text = r.render()
        assert "# HELP x_total counts x" in text
        assert "# TYPE x_total counter" in text
        assert "x_total 1" in text
        r.reset()
        assert "x_total 1" not in r.render()

    def test_duplicate_name_rejected(self):
        r = Registry()
        Counter("dup_total", "first", registry=r)
        with pytest.raises(ValueError, match="dup_total"):
            Counter("dup_total", "second", registry=r)
        # the rejected collector must not have been half-registered
        assert r.render().count("# TYPE dup_total") == 1

    def test_duplicate_across_types_rejected(self):
        r = Registry()
        Counter("dup2", "as counter", registry=r)
        with pytest.raises(ValueError):
            Gauge("dup2", "as gauge", registry=r)


# --- text exposition, checked by parsing (not substring matching) ----------
#
# A tiny exposition parser: enough of the Prometheus text format to
# round-trip what Registry.render() emits. Char-by-char label parsing so
# escaped quotes/backslashes inside label VALUES are exercised for real —
# a substring assertion would pass even if escaping were broken.


def _parse_labels(s: str) -> dict:
    """``{a="x",b="y"}`` body (no braces) -> dict, undoing escapes."""
    out = {}
    i = 0
    while i < len(s):
        eq = s.index("=", i)
        name = s[i:eq]
        assert s[eq + 1] == '"'
        i = eq + 2
        val = []
        while s[i] != '"':
            if s[i] == "\\":
                nxt = s[i + 1]
                val.append({"\\": "\\", '"': '"', "n": "\n"}[nxt])
                i += 2
            else:
                val.append(s[i])
                i += 1
        out[name] = "".join(val)
        i += 1  # closing quote
        if i < len(s):
            assert s[i] == ","
            i += 1
    return out


def _parse_exposition(text: str) -> dict:
    """Prometheus text -> {sample_name: [(labels_dict, float_value)]}."""
    samples: dict = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        metric, _, value = line.rpartition(" ")
        if "{" in metric:
            name, _, rest = metric.partition("{")
            assert rest.endswith("}")
            labels = _parse_labels(rest[:-1])
        else:
            name, labels = metric, {}
        v = float("inf") if value == "+Inf" else float(value)
        samples.setdefault(name, []).append((labels, v))
    return samples


class TestExposition:
    def test_label_escaping_round_trip(self):
        raw = 'back\\slash "quoted"\nnewline'
        c = Counter("esc_total", "h", labels=("path",), registry=None)
        c.inc(raw)
        samples = _parse_exposition(c.render())
        (labels, value), = samples["esc_total"]
        assert labels == {"path": raw}
        assert value == 1

    def test_histogram_buckets_cumulative_and_inf(self):
        h = Histogram("e_seconds", "h", buckets=[0.1, 1.0, 10.0],
                      labels=("route",), registry=None)
        for v in (0.05, 0.05, 0.5, 5.0, 500.0):
            h.observe("r1", v)
        samples = _parse_exposition(h.render())
        buckets = [
            (labels["le"], val)
            for labels, val in samples["e_seconds_bucket"]
            if labels["route"] == "r1"
        ]
        # rendered in ascending-bound order, counts monotone nondecreasing
        counts = [val for _, val in buckets]
        assert counts == sorted(counts)
        by_le = dict(buckets)
        assert by_le["0.1"] == 2
        assert by_le["1"] == 3
        assert by_le["10"] == 4
        (_, count_val), = samples["e_seconds_count"]
        assert by_le["+Inf"] == count_val == 5

    def test_histogram_sum_formatting(self):
        h = Histogram("s_seconds", "h", buckets=[1.0], registry=None)
        h.observe(0.25)
        h.observe(0.5)
        samples = _parse_exposition(h.render())
        (_, sum_val), = samples["s_seconds_sum"]
        assert sum_val == pytest.approx(0.75)
        # integral sums render without a trailing .0 (repr(int) path) but
        # must still parse as the same float
        h2 = Histogram("s2_seconds", "h", buckets=[10.0], registry=None)
        h2.observe(2)
        h2.observe(3)
        text = h2.render()
        assert "s2_seconds_sum 5\n" in text
        (_, sum2), = _parse_exposition(text)["s2_seconds_sum"]
        assert sum2 == 5.0
