"""Top-k / nucleus (top-p) sampling filters.

filter_logits is the one home for the math; behavioral pins: top_k=1
is greedy at any temperature, a nucleus no wider than the argmax is
greedy, disabled knobs are the identity, and the continuous batcher
applies per-slot values.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from kubeinfer_tpu.inference import PRESETS, init_params
from kubeinfer_tpu.inference.engine import (
    Engine,
    TOP_K_CAP,
    filter_logits,
    gumbel_sample,
)

TINY = PRESETS["tiny"]


def _logits(seed=0, B=2, V=32):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=(B, V)).astype(np.float32))


class TestFilterLogits:
    def test_disabled_is_identity(self):
        x = _logits()
        y = filter_logits(x, jnp.int32(0), jnp.float32(1.0))
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))

    def test_top_k_keeps_exactly_k(self):
        x = _logits(1)
        for k in (1, 3, 7):
            y = np.asarray(filter_logits(x, jnp.int32(k), jnp.float32(1.0)))
            assert ((y > -np.inf).sum(axis=-1) == k).all()
            # the survivors are the k largest
            for b in range(x.shape[0]):
                top = np.argsort(np.asarray(x[b]))[-k:]
                assert set(np.nonzero(y[b] > -np.inf)[0]) == set(top)

    def test_top_k_above_cap_clips(self):
        V = TOP_K_CAP * 2
        x = _logits(2, B=1, V=V)
        y = np.asarray(
            filter_logits(x, jnp.int32(V), jnp.float32(1.0))
        )
        assert (y > -np.inf).sum() == TOP_K_CAP

    def test_top_p_keeps_minimal_nucleus(self):
        # known distribution so the nucleus boundary is exact
        x = jnp.log(jnp.asarray([[0.5, 0.25, 0.15, 0.1]], jnp.float32))
        y = np.asarray(filter_logits(x, jnp.int32(0), jnp.float32(0.7)))
        # cumulative(exclusive): 0, .5, .75 -> keep p0, p1, and p2 (the
        # first whose exclusive sum .75 >= .7 is dropped)
        assert (y[0] > -np.inf).tolist() == [True, True, False, False]

    def test_top_p_always_keeps_argmax(self):
        x = _logits(3)
        y = np.asarray(filter_logits(x, jnp.int32(0), jnp.float32(1e-6)))
        kept = (y > -np.inf)
        assert (kept.sum(axis=-1) == 1).all()
        assert (np.argmax(np.asarray(x), -1) == np.argmax(y, -1)).all()

    def test_per_row_knobs(self):
        x = _logits(4, B=3)
        y = np.asarray(filter_logits(
            x, jnp.asarray([1, 0, 4], jnp.int32),
            jnp.asarray([1.0, 1e-6, 1.0], jnp.float32),
        ))
        assert (y[0] > -np.inf).sum() == 1  # top_k=1
        assert (y[1] > -np.inf).sum() == 1  # nucleus = argmax
        assert (y[2] > -np.inf).sum() == 4  # top_k=4


class TestSamplingBehavior:
    def test_top_k_one_is_greedy_at_any_temperature(self):
        x = _logits(5)
        key = jax.random.PRNGKey(0)
        got = gumbel_sample(x, key, jnp.float32(5.0), top_k=1)
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(jnp.argmax(x, -1))
        )

    @pytest.mark.slow
    def test_samples_stay_inside_top_k(self):
        x = _logits(6, B=1, V=16)
        top3 = set(np.argsort(np.asarray(x[0]))[-3:].tolist())
        for s in range(40):
            t = gumbel_sample(
                x, jax.random.PRNGKey(s), jnp.float32(2.0), top_k=3
            )
            assert int(t[0]) in top3

    def test_engine_generate_top_k_one_matches_greedy(self):
        params = init_params(TINY, jax.random.PRNGKey(0))
        eng = Engine(params, TINY)
        prompts = [[4, 5, 6, 7]]
        ref = eng.generate(prompts, max_new_tokens=6)  # greedy
        got = eng.generate(
            prompts, max_new_tokens=6, temperature=1.5, top_k=1
        )
        np.testing.assert_array_equal(got.tokens, ref.tokens)

    def test_continuous_engine_per_slot_filters(self):
        from kubeinfer_tpu.inference.batching import ContinuousEngine

        params = init_params(TINY, jax.random.PRNGKey(0))
        eng = Engine(params, TINY)
        cont = ContinuousEngine(params, TINY, n_slots=2, cache_len=64)
        cont.start()
        try:
            ref = eng.generate([[3, 4, 5]], max_new_tokens=5)
            # top_k=1 at high temperature must equal greedy even through
            # the slot path
            got = cont.generate(
                [3, 4, 5], max_new_tokens=5, temperature=3.0, top_k=1
            )
            assert got == ref.tokens[0].tolist()
        finally:
            cont.stop()

    def test_top_p_zero_still_samples_argmax(self):
        # top_p <= 0 collapsed to an all -inf row emitting token 0 before
        # the argmax-always-survives guard (r2 review finding)
        x = _logits(7)
        y = np.asarray(filter_logits(x, jnp.int32(0), jnp.float32(0.0)))
        assert ((y > -np.inf).sum(axis=-1) == 1).all()
        assert (np.argmax(y, -1) == np.argmax(np.asarray(x), -1)).all()
        t = gumbel_sample(x, jax.random.PRNGKey(0), jnp.float32(2.0),
                          top_p=0.0)
        np.testing.assert_array_equal(
            np.asarray(t), np.asarray(jnp.argmax(x, -1))
        )


class TestRepetitionPenalty:
    def test_unit_semantics(self):
        from kubeinfer_tpu.inference.engine import apply_repetition_penalty

        x = jnp.asarray([[2.0, -1.0, 0.5, -3.0]], jnp.float32)
        seen = jnp.asarray([[True, True, False, False]])
        y = np.asarray(
            apply_repetition_penalty(x, seen, jnp.float32(2.0))
        )
        # seen positive halves, seen negative doubles, unseen untouched
        np.testing.assert_allclose(y, [[1.0, -2.0, 0.5, -3.0]])

    def test_disabled_is_identity(self):
        from kubeinfer_tpu.inference.engine import apply_repetition_penalty

        x = _logits(8)
        seen = jnp.ones(x.shape, bool)
        y = apply_repetition_penalty(x, seen, jnp.float32(1.0))
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))

    def test_strong_penalty_blocks_immediate_repeats_greedy(self):
        # with an overwhelming penalty a greedy decode can never emit
        # the same token twice (every emitted id's logit is crushed)
        params = init_params(TINY, jax.random.PRNGKey(0))
        eng = Engine(params, TINY)
        out = eng.generate(
            [[9, 9, 9]], max_new_tokens=12, repetition_penalty=1e9
        )
        toks = out.tokens[0].tolist()
        assert len(set(toks)) == len(toks), toks
        assert 9 not in toks  # prompt ids count as seen

    def test_penalty_one_matches_plain_greedy(self):
        params = init_params(TINY, jax.random.PRNGKey(0))
        eng = Engine(params, TINY)
        ref = eng.generate([[1, 2, 3]], max_new_tokens=8)
        got = eng.generate(
            [[1, 2, 3]], max_new_tokens=8, repetition_penalty=1.0
        )
        np.testing.assert_array_equal(got.tokens, ref.tokens)

    def test_continuous_matches_engine_greedy_with_penalty(self):
        from kubeinfer_tpu.inference.batching import ContinuousEngine

        params = init_params(TINY, jax.random.PRNGKey(0))
        eng = Engine(params, TINY)
        cont = ContinuousEngine(params, TINY, n_slots=2, cache_len=64)
        cont.start()
        try:
            ref = eng.generate(
                [[5, 6, 7]], max_new_tokens=6, repetition_penalty=1.7
            )
            got = cont.generate(
                [5, 6, 7], max_new_tokens=6, repetition_penalty=1.7
            )
            assert got == ref.tokens[0].tolist()
        finally:
            cont.stop()
