"""End-to-end slice (SURVEY.md §7): every layer live, in-process.

Sample CR (3 replicas, shared cache — mirroring the reference's
config/samples/ai_v1_llmservice_cache.yaml) → batched reconciler → JAX
solver placements → workload bindings → node agents spawn replica agents →
lease election → coordinator fabricates the model dir once and serves it →
followers sync over HTTP → replicas Ready → status Running. Then the
failure paths: coordinator kill (failover) and CR deletion (GC).
"""

import pathlib
import threading
import time

import pytest

from kubeinfer_tpu.agent import NodeAgent
from kubeinfer_tpu.api.types import (
    CacheStrategy,
    LLMService,
    LLMServiceSpec,
    SchedulerPolicy,
)
from kubeinfer_tpu.api.workload import Workload
from kubeinfer_tpu.controller import Controller
from kubeinfer_tpu.controlplane import Store
from kubeinfer_tpu.metrics import REGISTRY

FAST_LEASE = (1.5, 1.0, 0.1)


def fab_downloader(calls):
    def download(repo, path):
        calls.append(repo)
        p = pathlib.Path(path)
        p.mkdir(parents=True, exist_ok=True)
        (p / "config.json").write_bytes(b"{}")
        (p / "weights.bin").write_bytes(b"\x02" * 200_000)

    return download


def wait_until(pred, timeout=60.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def cluster(tmp_path):
    """3-node cluster with controller + node agents running as threads."""
    store = Store()
    calls: list[str] = []
    controller = Controller(store)
    stop = threading.Event()
    ctrl_thread = threading.Thread(
        target=controller.run, args=(stop,), kwargs={"tick_interval_s": 0.2},
        daemon=True,
    )
    agents = [
        NodeAgent(
            store,
            f"node-{i}",
            gpu_capacity=4,
            gpu_memory_bytes=64 << 30,
            model_root=str(tmp_path / f"node-{i}"),
            downloader=fab_downloader(calls),
            heartbeat_interval_s=0.2,
            lease_timings=FAST_LEASE,
        )
        for i in range(3)
    ]
    for a in agents:
        a.start()
    ctrl_thread.start()
    yield store, calls, agents
    stop.set()
    for a in agents:
        a.stop()
    ctrl_thread.join(timeout=10)


def sample_cr() -> LLMService:
    """config/samples/ai_v1_llmservice_cache.yaml: 3 replicas, shared."""
    svc = LLMService()
    svc.metadata.name = "deepseek-cache"
    svc.spec = LLMServiceSpec(
        model="deepseek-ai/deepseek-r1-distill",
        replicas=3,
        gpu_per_replica=2,
        cache_strategy=CacheStrategy.SHARED,
        gpu_memory="16Gi",
        scheduler_policy=SchedulerPolicy.JAX_GREEDY,
    )
    svc.validate()
    return svc


class TestEndToEndSlice:
    def test_cr_to_running_with_single_hub_download(self, cluster):
        store, calls, agents = cluster
        store.create(LLMService.KIND, sample_cr().to_dict())

        def running():
            svc = LLMService.from_dict(store.get(LLMService.KIND, "deepseek-cache"))
            return svc.status.phase == "Running"

        assert wait_until(running), LLMService.from_dict(
            store.get(LLMService.KIND, "deepseek-cache")
        ).to_dict()

        svc = LLMService.from_dict(store.get(LLMService.KIND, "deepseek-cache"))
        assert svc.status.available_replicas == 3
        assert len([p for p in svc.status.placements if p]) == 3
        assert svc.status.cache_coordinator.startswith("deepseek-cache-")
        assert svc.status.get_condition("Available").status == "True"
        # shared cache did its job: exactly one WAN download for 3 replicas
        assert calls == ["deepseek-ai/deepseek-r1-distill"]
        # metrics flowed end to end
        text = REGISTRY.render()
        assert 'kubeinfer_model_download_duration_seconds_count{source="hub"}' in text
        assert 'source="coordinator"' in text or calls.count(
            "deepseek-ai/deepseek-r1-distill"
        ) == 1

    def test_coordinator_node_failure_recovers(self, cluster):
        store, calls, agents = cluster
        store.create(LLMService.KIND, sample_cr().to_dict())

        def running():
            svc = LLMService.from_dict(store.get(LLMService.KIND, "deepseek-cache"))
            return svc.status.phase == "Running"

        assert wait_until(running)
        coordinator = LLMService.from_dict(
            store.get(LLMService.KIND, "deepseek-cache")
        ).status.cache_coordinator

        # find and kill the node agent hosting the coordinator replica
        w = Workload.from_dict(store.get(Workload.KIND, "deepseek-cache"))
        coord_node = next(r.node for r in w.replicas if r.pod_name == coordinator)
        victim = next(a for a in agents if a.node_name == coord_node)
        victim.stop()

        def new_coordinator_elected():
            svc = LLMService.from_dict(store.get(LLMService.KIND, "deepseek-cache"))
            return (
                svc.status.cache_coordinator
                and svc.status.cache_coordinator != coordinator
            )

        assert wait_until(new_coordinator_elected, timeout=30)

    def test_cr_deletion_tears_everything_down(self, cluster):
        store, calls, agents = cluster
        store.create(LLMService.KIND, sample_cr().to_dict())
        assert wait_until(
            lambda: LLMService.from_dict(
                store.get(LLMService.KIND, "deepseek-cache")
            ).status.phase
            == "Running"
        )
        store.delete(LLMService.KIND, "deepseek-cache")
        assert wait_until(lambda: store.list(Workload.KIND) == [])
        # node agents reap their replica agents on the next tick
        assert wait_until(
            lambda: all(len(a._agents) == 0 for a in agents), timeout=10
        )

    def test_placements_and_roles_stable_under_load(self, cluster):
        """Regression: the heartbeat->solve feedback loop must not
        oscillate placements (double-counted capacity), and lease renewal
        must survive host load without role flips."""
        store, calls, agents = cluster
        store.create(LLMService.KIND, sample_cr().to_dict())
        assert wait_until(
            lambda: LLMService.from_dict(
                store.get(LLMService.KIND, "deepseek-cache")
            ).status.phase
            == "Running"
        )
        svc = LLMService.from_dict(store.get(LLMService.KIND, "deepseek-cache"))
        placements0 = svc.status.placements
        coordinator0 = svc.status.cache_coordinator
        deadline = time.time() + 5.0
        while time.time() < deadline:
            svc = LLMService.from_dict(
                store.get(LLMService.KIND, "deepseek-cache")
            )
            assert svc.status.placements == placements0, "placements moved"
            assert svc.status.cache_coordinator == coordinator0, "role flip"
            assert svc.status.phase == "Running"
            time.sleep(0.25)
