"""Native inference server: endpoint surface + runtime-launcher integration.

The endpoint surface is the one the reference's mock pins
(test/testdata/vllm-mock/mock_server.py: /health, /v1/models) plus real
/v1/completions; the integration test proves the agent's RuntimeServer
can spawn the native engine via RUNTIME_KIND=native with zero lifecycle
changes.
"""

from __future__ import annotations

import json
import sys
import time
import urllib.error
import urllib.request

import jax
import pytest

from kubeinfer_tpu.agent.runtime import RuntimeConfig
from kubeinfer_tpu.inference import PRESETS, init_params
from kubeinfer_tpu.inference.engine import Engine
from kubeinfer_tpu.inference.server import InferenceServer

TINY = PRESETS["tiny"]


@pytest.fixture(scope="module")
def server():
    params = init_params(TINY, jax.random.PRNGKey(0))
    srv = InferenceServer(
        Engine(params, TINY), model_id="tiny-test", port=0
    ).start()
    yield srv
    srv.stop()


def get(url: str):
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.status, json.loads(r.read() or b"null")


def post(url: str, body: dict):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


class TestEndpoints:
    def test_health(self, server):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/health", timeout=10
        ) as r:
            assert r.read() == b"OK"  # mock_server.py:8-15 parity

    def test_models_list(self, server):
        status, body = get(f"http://127.0.0.1:{server.port}/v1/models")
        assert status == 200
        assert body["object"] == "list"
        assert body["data"][0]["id"] == "tiny-test"  # mock_server.py:17-29

    def test_completion_with_token_ids(self, server):
        status, body = post(
            f"http://127.0.0.1:{server.port}/v1/completions",
            {"prompt": [1, 2, 3, 4], "max_tokens": 4},
        )
        assert status == 200
        choice = body["choices"][0]
        assert len(choice["tokens"]) == 4
        assert body["usage"] == {
            "prompt_tokens": 4, "completion_tokens": 4, "total_tokens": 8,
        }
        # deterministic greedy: same request → same tokens
        _, body2 = post(
            f"http://127.0.0.1:{server.port}/v1/completions",
            {"prompt": [1, 2, 3, 4], "max_tokens": 4},
        )
        assert body2["choices"][0]["tokens"] == choice["tokens"]

    def test_string_prompt_without_tokenizer_rejected(self, server):
        status, body = post(
            f"http://127.0.0.1:{server.port}/v1/completions",
            {"prompt": "hello", "max_tokens": 2},
        )
        assert status == 400
        assert "tokenizer" in body["error"]["message"]

    def test_missing_prompt_rejected(self, server):
        status, _ = post(
            f"http://127.0.0.1:{server.port}/v1/completions", {"max_tokens": 2}
        )
        assert status == 400


class TestRuntimeLauncherIntegration:
    @pytest.mark.slow
    def test_runtime_kind_native_spawns_real_server(self, tmp_path, monkeypatch):
        """RUNTIME_KIND=native + the standard env contract boots the
        native engine as a subprocess through the unchanged RuntimeServer
        lifecycle (vllm.go Start/Stop parity)."""
        import socket

        from tests.conftest import scrubbed_pythonpath

        monkeypatch.setenv("PYTHONPATH", scrubbed_pythonpath())

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]

        cfg = RuntimeConfig.from_env({
            "RUNTIME_KIND": "native",
            "MODEL_PATH": "tiny",  # preset name + --random-init below
            "VLLM_HOST": "127.0.0.1",
            "VLLM_PORT": str(port),
            "VLLM_EXTRA_ARGS": "--random-init",
            "VLLM_DTYPE": "float32",
        })
        assert cfg.command_prefix[-1] == "kubeinfer_tpu.inference.server"

        from kubeinfer_tpu.agent.runtime import RuntimeServer

        srv = RuntimeServer(cfg)
        srv.start()
        try:
            deadline = time.monotonic() + 120
            up = False
            while time.monotonic() < deadline:
                try:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/health", timeout=2
                    ) as r:
                        up = r.read() == b"OK"
                        break
                except OSError:
                    time.sleep(0.5)
            assert up, "native runtime never became healthy"
            status, body = post(
                f"http://127.0.0.1:{port}/v1/completions",
                {"prompt": [5, 6, 7], "max_tokens": 3},
            )
            assert status == 200
            assert len(body["choices"][0]["tokens"]) == 3
        finally:
            srv.stop()
        assert not srv.running()

    def test_unknown_runtime_kind_rejected(self):
        with pytest.raises(ValueError, match="RUNTIME_KIND"):
            RuntimeConfig.from_env({"RUNTIME_KIND": "tgi"})


class TestSpeculativeServing:
    @pytest.fixture(scope="class")
    def spec_server(self):
        from kubeinfer_tpu.inference.speculative import SpeculativeEngine

        params = init_params(TINY, jax.random.PRNGKey(0))
        engine = Engine(params, TINY)
        # self-draft: acceptance 1.0, output must equal vanilla greedy
        spec = SpeculativeEngine(params, TINY, params, TINY, k=3)
        srv = InferenceServer(
            engine, model_id="tiny-spec", port=0, speculative=spec
        ).start()
        yield srv, engine
        srv.stop()

    def test_greedy_request_routes_through_speculation(self, spec_server):
        srv, engine = spec_server
        body = {"prompt": [5, 6, 7], "max_tokens": 8, "temperature": 0.0}
        code, resp = post(
            f"http://127.0.0.1:{srv.port}/v1/completions", body
        )
        assert code == 200
        ref = engine.generate([[5, 6, 7]], max_new_tokens=8)
        assert resp["choices"][0]["tokens"] == ref.tokens[0].tolist()
        # the speculative path actually ran (stats recorded)
        assert srv.speculative.last_stats["rounds"] >= 1

    def test_sampled_request_takes_speculation(self, spec_server):
        """Sampled requests ride the draft too since r3's rejection-
        sampling correction (speculative.py) — only repetition-penalty
        requests still skip it."""
        srv, _ = spec_server
        srv.speculative.last_stats = None
        body = {
            "prompt": [5, 6, 7], "max_tokens": 4,
            "temperature": 0.8, "seed": 7,
        }
        code, resp = post(
            f"http://127.0.0.1:{srv.port}/v1/completions", body
        )
        assert code == 200
        assert len(resp["choices"][0]["tokens"]) >= 1
        assert srv.speculative.last_stats is not None  # path taken

    def test_repetition_penalty_skips_speculation(self, spec_server):
        srv, _ = spec_server
        srv.speculative.last_stats = None
        body = {
            "prompt": [5, 6, 7], "max_tokens": 4,
            "repetition_penalty": 1.3,
        }
        code, resp = post(
            f"http://127.0.0.1:{srv.port}/v1/completions", body
        )
        assert code == 200
        assert len(resp["choices"][0]["tokens"]) >= 1
        assert srv.speculative.last_stats is None  # path not taken


class TestServingMetrics:
    def test_metrics_endpoint_counts_requests(self, server):
        code, _ = post(
            f"http://127.0.0.1:{server.port}/v1/completions",
            {"prompt": [1, 2, 3], "max_tokens": 3},
        )
        assert code == 200
        import urllib.request

        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics", timeout=10
        ) as r:
            body = r.read().decode()
        assert 'kubeinfer_inference_requests_total{route="engine",outcome="ok"}' in body
        assert "kubeinfer_inference_completion_tokens_total" in body
        assert "kubeinfer_inference_request_seconds_bucket" in body

    def test_invalid_requests_counted(self, server):
        before = server.metrics["requests"].value("invalid", "invalid")
        code, _ = post(
            f"http://127.0.0.1:{server.port}/v1/completions",
            {"prompt": [1], "max_tokens": 2, "top_p": 7.0},
        )
        assert code == 400
        assert server.metrics["requests"].value("invalid", "invalid") == before + 1

    def test_generation_errors_carry_route_label(self, server, monkeypatch):
        # an engine failure AFTER route selection must be counted under
        # that route, not the "invalid" sentinel (r2 review finding)
        def boom(*a, **kw):
            raise RuntimeError("engine exploded")

        monkeypatch.setattr(server.engine, "generate", boom)
        before = server.metrics["requests"].value("engine", "error")
        code, _ = post(
            f"http://127.0.0.1:{server.port}/v1/completions",
            {"prompt": [1, 2], "max_tokens": 2},
        )
        assert code == 500
        assert server.metrics["requests"].value("engine", "error") == before + 1

    def test_malformed_json_counted(self, server):
        import urllib.request

        before = server.metrics["requests"].value("invalid", "invalid")
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/completions",
            data=b"{not json", method="POST",
            headers={"Content-Type": "application/json"},
        )
        try:
            urllib.request.urlopen(req, timeout=10)
            code = 200
        except urllib.error.HTTPError as e:
            code = e.code
        assert code == 400
        assert server.metrics["requests"].value("invalid", "invalid") == before + 1


class TestBatcherOwnsDraftTraffic:
    def test_eligible_requests_route_to_batcher_groups(self):
        """With a batcher configured, draft-eligible requests route
        'continuous' and ride the batcher's incremental spec groups
        (visible in the spec gauges) — the serialized bulk 'speculative'
        route remains only for batcher-less servers (r4 verdict item 5:
        speculation must survive load, and the batcher is where load
        lives)."""
        from kubeinfer_tpu.inference.batching import ContinuousEngine
        from kubeinfer_tpu.inference.server import InferenceServer
        from kubeinfer_tpu.inference.speculative import SpeculativeEngine

        cfg = PRESETS["tiny"]
        params = init_params(cfg, jax.random.PRNGKey(0))
        spec = SpeculativeEngine(params, cfg, params, cfg, k=2)
        cont = ContinuousEngine(
            params, cfg, n_slots=2, cache_len=256, speculative=spec
        ).start()
        srv = InferenceServer(
            Engine(params, cfg), model_id="tiny", port=0,
            continuous=cont, speculative=spec,
        )
        try:
            resp = srv.complete({"prompt": [5, 6, 7], "max_tokens": 5})
            assert resp["usage"]["completion_tokens"] == 5
            m = srv.registry.render().replace("'", '"')
            assert 'route="continuous",outcome="ok"' in m
            assert 'route="speculative"' not in m
            srv._refresh_spec_metrics()
            out = srv.registry.render()
            assert "spec_served_requests 1" in out, out.splitlines()[-4:]
        finally:
            cont.stop()
