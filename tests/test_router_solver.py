"""Solver-routed fleet: batched route solve vs the per-request scorer.

Three layers, mirroring the PR's claim structure:

- plane building: the batched FNV fingerprint chain is bit-identical
  to ``kv_blocks.prefix_fingerprints`` (same residue arithmetic — the
  docstring in solver/routing.py argues why uint64 wraparound is
  exact), and the match plane reproduces ``scoring.match_depth``.
- the solve: the Pallas row-argmax kernel is bit-identical to its jnp
  twin (interpret mode — the parity argument in pallas_kernels.py is
  comparison-only, so CPU equality IS TPU equality), and all three
  modes agree between accel paths.
- the router: ``route_batch`` decisions equal ``route()``'s — the B=1
  degenerate case byte-compatible (dataclass equality), the batch case
  equal to the per-request loop over an identical snapshot, with every
  gate (stale, dead, draining, breaker, exclude, tie-break-by-name)
  exercised.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from kubeinfer_tpu.inference.kv_blocks import prefix_fingerprints
from kubeinfer_tpu.router import FleetRouter, RouterServer, scoring
from kubeinfer_tpu.router.server import _StormBatcher
from kubeinfer_tpu.solver import pallas_kernels as pk
from kubeinfer_tpu.solver import routing
from kubeinfer_tpu.utils.clock import SimulatedClock


def summary_of(*paths: list[int], block_size: int = 4) -> dict:
    return {
        "fingerprints": sorted(
            {fp for p in paths
             for fp in prefix_fingerprints(p, block_size)}
        ),
        "version": 1,
        "block_size": block_size,
    }


def serving(queue_depth=0, n_slots=2, summary=None, **extra) -> dict:
    d = {"queue_depth": queue_depth, "n_slots": n_slots, **extra}
    if summary is not None:
        d["cache_summary"] = summary
    return d


def mk_router(clock=None):
    clk = clock or SimulatedClock(start=100.0)
    return FleetRouter(clock=clk.now), clk


class TestBatchedFingerprints:
    def test_bit_identical_to_per_request_chain(self):
        rng = np.random.default_rng(7)
        batch = [
            rng.integers(0, 60_000, int(n)).tolist()
            for n in rng.integers(0, 90, 24)
        ] + [[], [1, 2, 3]]
        for bs in (1, 3, 4, 32):
            got = routing.batched_prefix_fingerprints(batch, bs, 4096)
            for b, toks in enumerate(batch):
                ref = prefix_fingerprints(toks, bs)
                assert [int(x) for x in got[b] if x != -1] == ref

    def test_rectangular_fast_path_matches(self):
        rng = np.random.default_rng(8)
        batch = [rng.integers(0, 60_000, 64).tolist() for _ in range(9)]
        got = routing.batched_prefix_fingerprints(batch, 16, 4096)
        for b, toks in enumerate(batch):
            assert got[b].tolist() == prefix_fingerprints(toks, 16)

    def test_depth_clip(self):
        toks = list(range(64))
        got = routing.batched_prefix_fingerprints([toks], 4, 3)
        assert [int(x) for x in got[0] if x != -1] == \
            prefix_fingerprints(toks, 4)[:3]

    def test_match_plane_equals_scoring_match_depth(self):
        rng = np.random.default_rng(9)
        fams = [rng.integers(0, 60_000, 32).tolist() for _ in range(4)]
        fp_sets = [
            frozenset(prefix_fingerprints(fams[i % 4][: 8 * (i + 1)], 8))
            for i in range(3)
        ] + [frozenset()]
        bss = [8, 8, 8, 0]
        batch = [f + [1, 2, 3] for f in fams]
        plane = routing.build_match_plane(batch, fp_sets, bss)
        for b, toks in enumerate(batch):
            for r in range(4):
                want = (
                    scoring.match_depth(
                        prefix_fingerprints(toks, bss[r]), fp_sets[r]
                    ) if bss[r] else 0
                )
                assert plane[b, r] == want


class TestRoutePickParity:
    """The new Pallas kernel vs its jnp twin — exact array equality in
    interpret mode, per the solver invariant (CLAUDE.md)."""

    @pytest.mark.parametrize("shape", [(8, 128), (64, 256), (128, 128)])
    def test_kernel_bit_identical_incl_ties(self, shape):
        B, R = shape
        rng = np.random.default_rng(B + R)
        # coarse match values force score ties across columns; bias in
        # exact-f32 halves keeps the tie exact rather than rounded
        match = rng.integers(-1, 4, (B, R)).astype(np.int32)
        bias = (rng.integers(-8, 8, R) / 2.0).astype(np.float32)
        active = rng.random(B) < 0.9
        match[~active] = -1
        match[B // 2] = -1  # an active row with zero candidates
        v_j, i_j = pk.route_pick_jnp(match, bias, active)
        v_p, i_p = pk.route_pick_pallas(match, bias, active,
                                        interpret=True)
        np.testing.assert_array_equal(np.asarray(i_j), np.asarray(i_p))
        np.testing.assert_array_equal(np.asarray(v_j), np.asarray(v_p))

    def test_pallas_rejects_unaligned(self):
        with pytest.raises(ValueError):
            pk.route_pick_pallas(
                np.zeros((7, 128), np.int32), np.zeros(128, np.float32),
                np.ones(7, bool),
            )

    @pytest.mark.parametrize("mode", ["parity", "greedy", "auction"])
    def test_solve_modes_agree_across_accels(self, mode):
        rng = np.random.default_rng(3)
        match = rng.integers(-1, 9, (33, 100)).astype(np.int32)
        rp, _, _ = routing.pack_route_arrays(
            match,
            (rng.integers(0, 6, 100) / 2.0).astype(np.float32),
            rng.random(100) < 0.2,
            np.full(100, 2.0, np.float32),
            rng.random(100).astype(np.float32),
        )
        a = routing.solve_routes(rp, mode=mode, accel="jnp")
        b = routing.solve_routes(rp, mode=mode, accel="interpret")
        np.testing.assert_array_equal(
            np.asarray(a.replica), np.asarray(b.replica)
        )
        np.testing.assert_array_equal(
            np.asarray(a.score), np.asarray(b.score)
        )


class TestRouteBatchEquivalence:
    def plant_fleet(self):
        """Every gate on the board: warm, tied pair, busy, stale, dead,
        draining, breaker-open."""
        r, clk = mk_router()
        toks = list(range(16))
        r.add_replica("dead", "http://dead")
        r.update_replica("dead", serving(summary=summary_of(toks)))
        clk.advance(scoring.DEAD_AFTER_S + 1)
        r.add_replica("stale", "http://stale")
        r.update_replica("stale", serving(summary=summary_of(toks)))
        clk.advance(scoring.STALE_AFTER_S + 1)
        for name, qd, summ in [
            ("warm", 0, summary_of(toks)),
            ("tie-b", 1, summary_of(toks[:8])),
            ("tie-a", 1, summary_of(toks[:8])),
            ("busy", 6, summary_of(toks)),
            ("drain", 0, summary_of(toks)),
            ("broken", 0, summary_of(toks)),
        ]:
            r.add_replica(name, f"http://{name}")
            r.update_replica(name, serving(queue_depth=qd, summary=summ))
        r.mark_draining("drain")
        broken = next(v for v in r.replicas() if v.name == "broken")
        for _ in range(3):
            broken.breaker.record_failure()
        return r, toks

    def test_solver_python_and_single_request_agree(self):
        r, toks = self.plant_fleet()
        batch = [toks, toks[:8], [7] * 16, toks[:4]]
        singles = [r.route(t) for t in batch]
        for engine in ("python", "solver"):
            got = r.route_batch(batch, engine=engine)
            assert got == singles, engine

    def test_tie_breaks_by_name_both_engines(self):
        r, toks = self.plant_fleet()
        # exclude everything that beats the tied pair: the equal-score
        # tie must go to "tie-a" (lowest name) everywhere
        ex = frozenset({"warm", "busy"})
        assert r.route(toks, exclude=ex).replica == "tie-a"
        for engine in ("python", "solver"):
            got = r.route_batch([toks, toks], [ex, ex], engine=engine)
            assert [d.replica for d in got] == ["tie-a", "tie-a"], engine

    def test_dead_dropout_and_masks_in_batch(self):
        r, toks = self.plant_fleet()
        picks = {
            d.replica for d in r.route_batch([toks] * 6, engine="solver")
        }
        assert picks == {"warm"}
        assert r.metrics["skipped"].value("dead", "dead") == 6
        assert r.metrics["skipped"].value("drain", "draining") == 6
        assert r.metrics["skipped"].value("broken", "breaker") == 6

    def test_b1_degenerate_case_byte_compatible(self):
        """The pinned acceptance case: a single-request batch returns
        the exact RouteDecision dataclass route() returns — every
        field, fallback and stale flags included."""
        r, toks = self.plant_fleet()
        for t in (toks, [9] * 16, toks[:8]):
            want = r.route(t)
            for engine in ("python", "solver"):
                got = r.route_batch([t], engine=engine)
                assert got == [want], engine

    def test_empty_batch_and_empty_fleet(self):
        r, _ = mk_router()
        assert r.route_batch([]) == []
        assert r.route_batch([[1, 2, 3, 4]]) == [None]

    def test_per_request_excludes(self):
        r, toks = self.plant_fleet()
        got = r.route_batch(
            [toks, toks], [frozenset(), frozenset({"warm"})],
            engine="solver",
        )
        assert got[0].replica == "warm"
        assert got[1].replica != "warm"

    def test_unknown_engine_and_mode_raise(self):
        r, toks = self.plant_fleet()
        with pytest.raises(ValueError):
            r.route_batch([toks], engine="carrier-pigeon")
        with pytest.raises(ValueError):
            r.route_batch([toks], engine="solver", mode="chaotic")

    def test_constants_pinned_to_scoring(self):
        """solver/routing.py cannot import router/scoring (layering:
        scoring stays numpy/jax-free for the reconciler tick path), so
        its numeric defaults are duplicated — this is the pin."""
        import inspect

        sig = inspect.signature(routing.solve_routes)
        assert sig.parameters["alpha"].default == \
            scoring.ALPHA_QUEUE_BLOCKS
        assert sig.parameters["stale_penalty"].default == \
            scoring.STALE_PENALTY_BLOCKS


class TestHeadroomGamma:
    """--headroom-weight satellite: the KV-fullness plane is inert at
    the default gamma=0 (byte-compatible scores) and, when armed, steers
    identical-cache picks toward the replica with free KV — with the
    python scorer, the batched solver, and route() all agreeing."""

    def plant(self, gamma=0.0):
        clk = SimulatedClock(start=100.0)
        r = FleetRouter(clock=clk.now, gamma=gamma)
        toks = list(range(16))
        # identical caches and queues; only KV fullness differs, so the
        # gamma plane is the ONLY discriminator ("full" wins the
        # name-order tie-break at gamma=0)
        for name, free, used in [("full", 10, 90), ("roomy", 90, 10)]:
            r.add_replica(name, f"http://{name}")
            r.update_replica(name, serving(
                summary=summary_of(toks),
                kv_blocks_free=free, kv_blocks_in_use=used,
            ))
        return r, toks

    def test_gamma_zero_is_byte_identical(self):
        r0, toks = self.plant(gamma=0.0)
        want = r0.route(toks)
        assert want.replica == "full"  # tie -> lowest name
        assert scoring.replica_score(3, 0.5, False) == \
            scoring.replica_score(3, 0.5, False, gamma=0.0, headroom=0.1)

    def test_gamma_steers_to_free_kv(self):
        r, toks = self.plant(gamma=8.0)
        got = r.route(toks)
        assert got.replica == "roomy"
        # score drop matches the documented plane: -gamma * (1 - headroom)
        depth = scoring.match_depth(
            prefix_fingerprints(toks, 4),
            frozenset(summary_of(toks)["fingerprints"]),
        )
        assert got.score == pytest.approx(scoring.replica_score(
            depth, 0.0, False, gamma=8.0, headroom=0.9,
        ))

    @pytest.mark.parametrize("gamma", [0.0, 2.5, 8.0])
    def test_solver_python_and_single_agree(self, gamma):
        r, toks = self.plant(gamma=gamma)
        batch = [toks, toks[:8], [7] * 16, toks[:4]]
        singles = [r.route(t) for t in batch]
        for engine in ("python", "solver"):
            assert r.route_batch(batch, engine=engine) == singles, \
                (engine, gamma)


class TestSpreadModes:
    def plant_identical(self, n=3, qd=0):
        r, _ = mk_router()
        toks = list(range(16))
        for i in range(n):
            r.add_replica(f"r{i}", f"http://r{i}")
            r.update_replica(
                f"r{i}", serving(queue_depth=qd,
                                 summary=summary_of(toks)),
            )
        return r, toks

    def test_parity_dogpiles_greedy_spreads(self):
        r, toks = self.plant_identical()
        batch = [toks] * 12
        parity = {
            d.replica
            for d in r.route_batch(batch, engine="solver", mode="parity")
        }
        assert parity == {"r0"}  # the documented per-request behavior
        greedy = [
            d.replica
            for d in r.route_batch(batch, engine="solver", mode="greedy")
        ]
        assert set(greedy) == {"r0", "r1", "r2"}
        counts = [greedy.count(f"r{i}") for i in range(3)]
        assert max(counts) - min(counts) <= 1  # slot-capped rounds

    def test_auction_assigns_everyone(self):
        r, toks = self.plant_identical(n=2)
        got = r.route_batch([toks] * 9, engine="solver", mode="auction")
        assert all(d is not None for d in got)
        assert {d.replica for d in got} == {"r0", "r1"}


class TestSolvedAffinity:
    def test_idle_cached_node_keeps_bit_hot_one_loses_it(self):
        cached = np.zeros((2, 8), np.uint8)
        cached[0, 3] = cached[1, 3] = 1
        out = routing.solved_affinity(
            np.array([3, 3], np.int32), cached,
            np.array([4.0, 0.0], np.float32),
            np.array([2.0, 2.0], np.float32),
            alpha=scoring.ALPHA_QUEUE_BLOCKS,
            cutoff=scoring.PRESSURE_AFFINITY_CUTOFF,
        )
        assert out[1, 3] == 1 and out[0, 3] == 0

    def test_relative_cutoff_sole_caching_node_keeps_pull(self):
        """The documented divergence from the old absolute gate: a
        drowning node with no cached alternative still wins its own
        pseudo-request, so the bit survives."""
        cached = np.zeros((2, 8), np.uint8)
        cached[0, 3] = 1
        out = routing.solved_affinity(
            np.array([3], np.int32), cached,
            np.array([2.0, 2.0], np.float32),  # both equally drowned
            np.array([2.0, 2.0], np.float32),
            alpha=scoring.ALPHA_QUEUE_BLOCKS,
            cutoff=scoring.PRESSURE_AFFINITY_CUTOFF,
        )
        assert out[0, 3] == 1

    def test_no_cache_anywhere_short_circuits(self):
        out = routing.solved_affinity(
            np.array([1, 2], np.int32), np.zeros((3, 8), np.uint8),
            np.zeros(3, np.float32), np.ones(3, np.float32),
            alpha=4.0, cutoff=1.0,
        )
        assert out.sum() == 0


class TestStormBatcher:
    def plant(self):
        r, _ = mk_router()
        toks_a, toks_b = list(range(16)), list(range(50, 66))
        for name, toks in [("a", toks_a), ("b", toks_b)]:
            r.add_replica(name, f"http://{name}")
            r.update_replica(name, serving(summary=summary_of(toks)))
        return r, toks_a, toks_b

    def test_concurrent_arrivals_share_one_solve(self):
        r, toks_a, toks_b = self.plant()
        sb = _StormBatcher(r, window_s=0.05)
        results: dict[int, object] = {}

        def go(i):
            results[i] = sb.route(
                toks_a if i % 2 == 0 else toks_b, frozenset()
            )

        threads = [
            threading.Thread(target=go, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10.0)
        assert len(results) == 8
        for i, d in results.items():
            assert d.replica == ("a" if i % 2 == 0 else "b")
        # one leader solved the lot: the batch gauge saw > 1 request
        assert r.metrics["batch_size"].value() > 1

    def test_empty_fleet_returns_none_for_fallback(self):
        r, _ = mk_router()
        sb = _StormBatcher(r, window_s=0.01)
        assert sb.route([1, 2, 3, 4], frozenset()) is None


class _StubTokenizer:
    def __init__(self, fail=False):
        self.fail = fail

    def encode(self, text: str) -> list[int]:
        if self.fail:
            raise RuntimeError("boom")
        return [ord(c) % 251 for c in text]


class TestTokenizerPath:
    def mk_server(self, tokenizer=None, **kw):
        r, _ = mk_router()
        toks = _StubTokenizer().encode("hello world, again and again")
        r.add_replica("warm", "http://warm")
        r.add_replica("cold", "http://cold")
        r.update_replica("warm", serving(summary=summary_of(toks)))
        r.update_replica(
            "cold", serving(summary=summary_of([9] * 8)),
        )
        srv = RouterServer(r, poll_interval_s=0, tokenizer=tokenizer,
                           **kw)
        # no sockets: the proxy leg is stubbed so forward() exercises
        # routing + note_routed without an upstream
        srv._proxy = lambda decision, raw: b'{"choices": []}'
        return srv, r

    def test_string_prompt_fingerprint_matches_with_tokenizer(self):
        srv, r = self.mk_server(tokenizer=_StubTokenizer())
        import json

        code, payload = srv.forward(json.dumps(
            {"prompt": "hello world, again and again", "max_tokens": 4}
        ).encode())
        assert code == 200
        assert json.loads(payload)["kubeinfer"]["replica"] == "warm"
        assert json.loads(payload)["kubeinfer"]["match_blocks"] > 0
        assert r.metrics["tokenizer_fallback"].value() == 0

    def test_tokenizer_feeds_optimistic_note_routed(self):
        """The asymmetry fix: a tokenizer-resolved prompt grows the
        chosen replica's optimistic fingerprint view, exactly like a
        token-id prompt always has."""
        srv, r = self.mk_server(tokenizer=_StubTokenizer())
        import json

        before = len(
            next(v for v in r.replicas() if v.name == "warm").fingerprints
        )
        srv.forward(json.dumps(
            {"prompt": "hello world, AND SOMETHING ENTIRELY NEW HERE!",
             "max_tokens": 4}
        ).encode())
        after = len(
            next(v for v in r.replicas() if v.name == "warm").fingerprints
        )
        assert after > before

    def test_no_tokenizer_counts_fallback(self):
        srv, r = self.mk_server(tokenizer=None)
        import json

        code, _ = srv.forward(json.dumps(
            {"prompt": "hello world, again and again"}
        ).encode())
        assert code == 200
        assert r.metrics["tokenizer_fallback"].value() == 1

    def test_encode_failure_counts_fallback_and_serves(self):
        srv, r = self.mk_server(tokenizer=_StubTokenizer(fail=True))
        import json

        code, _ = srv.forward(json.dumps(
            {"prompt": "hello world, again and again"}
        ).encode())
        assert code == 200
        assert r.metrics["tokenizer_fallback"].value() == 1

    def test_storm_window_first_placement(self):
        srv, r = self.mk_server(tokenizer=_StubTokenizer(),
                                storm_window_s=0.02)
        import json

        code, payload = srv.forward(json.dumps(
            {"prompt": "hello world, again and again", "max_tokens": 4}
        ).encode())
        assert code == 200
        assert json.loads(payload)["kubeinfer"]["replica"] == "warm"
        assert r.metrics["solver_routed"].value("parity") >= 1
