"""Solver core tests: exact small cases, invariants on random instances,
priority/gang/hysteresis semantics, auction vs Hungarian oracle.

Runs on the 8-device virtual CPU backend (conftest); identical code path on
a real TPU chip.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from kubeinfer_tpu.solver import (
    Assignment,
    ScoreWeights,
    encode_problem,
    solve_auction,
    solve_greedy,
)
from kubeinfer_tpu.solver.problem import JobRow, NodeRow, bucket_size

EPS = 1e-3


def assert_invariants(p, jobs, nodes, a: Assignment):
    """Hard correctness invariants, valid for ANY assignment policy."""
    assigned = np.asarray(a.node)[: len(jobs)]
    gpu_used = np.zeros(len(nodes))
    mem_used = np.zeros(len(nodes))
    for j, n in enumerate(assigned):
        if n >= 0:
            assert n < len(nodes), "placed on padding node"
            gpu_used[n] += jobs[j].gpu
            mem_used[n] += jobs[j].mem_gib
    for i, node in enumerate(nodes):
        assert gpu_used[i] <= node.gpu_free + EPS, f"node {i} gpu over capacity"
        assert mem_used[i] <= node.mem_free_gib + EPS, f"node {i} mem over capacity"
    # padding jobs never placed
    full = np.asarray(a.node)
    assert (full[len(jobs):] == -1).all()
    assert int(a.placed) == int((assigned >= 0).sum())
    # reported remaining capacity is consistent
    np.testing.assert_allclose(
        np.asarray(a.gpu_free)[: len(nodes)],
        np.array([n.gpu_free for n in nodes]) - gpu_used,
        atol=1e-3,
    )


def greedy_fixpoint_check(jobs, nodes, a: Assignment):
    """At a greedy fixpoint, every unplaced non-gang job must be infeasible
    against the remaining capacity (proof sketch in core.py docstring)."""
    assigned = np.asarray(a.node)[: len(jobs)]
    gpu_left = np.asarray(a.gpu_free)[: len(nodes)]
    mem_left = np.asarray(a.mem_free)[: len(nodes)]
    for j, job in enumerate(jobs):
        if assigned[j] < 0 and job.gang < 0:
            fits = (job.gpu <= gpu_left + EPS) & (job.mem_gib <= mem_left + EPS)
            assert not fits.any(), f"job {j} unplaced but feasible"


class TestBucketing:
    def test_bucket_size(self):
        assert bucket_size(1) == 64
        assert bucket_size(64) == 64
        assert bucket_size(65) == 128
        assert bucket_size(10_000) == 12288
        with pytest.raises(ValueError):
            bucket_size(100_000)

    def test_encode_padding(self):
        p, table = encode_problem(
            [JobRow(gpu=1, model="m1")], [NodeRow(gpu_free=4, cached_models=["m1"])]
        )
        assert p.jobs.valid.shape == (64,)
        assert p.nodes.valid.shape == (64,)
        assert int(p.jobs.valid.sum()) == 1
        assert int(p.nodes.valid.sum()) == 1
        assert table == {"m1": 1}


class TestGreedySmall:
    def test_cache_affinity_wins(self):
        # Two identical nodes; node 1 has the model cached -> job goes there.
        jobs = [JobRow(gpu=1, mem_gib=10, model="llama")]
        nodes = [
            NodeRow(gpu_free=4, mem_free_gib=100),
            NodeRow(gpu_free=4, mem_free_gib=100, cached_models=["llama"]),
        ]
        p, _ = encode_problem(jobs, nodes)
        a = solve_greedy(p)
        assert int(a.node[0]) == 1
        assert_invariants(p, jobs, nodes, a)

    def test_best_fit(self):
        # Tight node preferred over roomy one (leftover capacity is cost).
        # noise=0 (floored at _MIN_TIE_NOISE=1e-3): the fit gap here (~0.75)
        # dwarfs the floor, so the exact ordering is still deterministic.
        jobs = [JobRow(gpu=2, mem_gib=10)]
        nodes = [NodeRow(gpu_free=8, mem_free_gib=100), NodeRow(gpu_free=2, mem_free_gib=100)]
        p, _ = encode_problem(jobs, nodes)
        a = solve_greedy(p, ScoreWeights(noise=0.0))
        assert int(a.node[0]) == 1

    def test_large_job_not_stranded_by_small_bidders(self):
        # FFD accept order: a contested node must go to its LARGEST bidder.
        # With ascending order the 8-chip job loses every whole-idle node to
        # trivially-relocatable small jobs and ends unplaced even though a
        # serial FFD places all four (regression: pre-fix this placed 3/4).
        jobs = [
            JobRow(gpu=2, mem_gib=8),
            JobRow(gpu=4, mem_gib=16),
            JobRow(gpu=1, mem_gib=4),
            JobRow(gpu=8, mem_gib=32),
        ]
        nodes = [NodeRow(gpu_free=8, mem_free_gib=64) for _ in range(2)]
        p, _ = encode_problem(jobs, nodes)
        a = solve_greedy(p)
        assert int(a.placed) == 4
        assert_invariants(p, jobs, nodes, a)

    def test_infeasible_unplaced(self):
        jobs = [JobRow(gpu=16, mem_gib=10)]
        nodes = [NodeRow(gpu_free=8, mem_free_gib=100)]
        p, _ = encode_problem(jobs, nodes)
        a = solve_greedy(p)
        assert int(a.node[0]) == -1
        assert int(a.placed) == 0

    def test_contention_splits_across_nodes(self):
        # 4 jobs of 2 chips; two 4-chip nodes -> 2 jobs per node.
        jobs = [JobRow(gpu=2, mem_gib=1) for _ in range(4)]
        nodes = [NodeRow(gpu_free=4, mem_free_gib=10) for _ in range(2)]
        p, _ = encode_problem(jobs, nodes)
        a = solve_greedy(p)
        assigned = np.asarray(a.node)[:4]
        assert (assigned >= 0).all()
        counts = np.bincount(assigned, minlength=2)
        assert list(counts[:2]) == [2, 2]
        assert_invariants(p, jobs, nodes, a)

    def test_priority_wins_contested_node(self):
        # One 1-chip node, two bidders; high priority gets it.
        jobs = [JobRow(gpu=1, priority=0), JobRow(gpu=1, priority=10)]
        nodes = [NodeRow(gpu_free=1, mem_free_gib=10)]
        p, _ = encode_problem(jobs, nodes)
        a = solve_greedy(p)
        assert int(a.node[0]) == -1
        assert int(a.node[1]) == 0

    def test_hysteresis_keeps_incumbent(self):
        # Job already on node 0; node 1 is a slightly tighter fit, but the
        # move penalty outweighs the fit gain -> stays home.
        jobs = [JobRow(gpu=2, mem_gib=1, current_node=0)]
        nodes = [NodeRow(gpu_free=4, mem_free_gib=10), NodeRow(gpu_free=2, mem_free_gib=10)]
        p, _ = encode_problem(jobs, nodes)
        a = solve_greedy(p)
        assert int(a.node[0]) == 0

    def test_preemption_by_resolve(self):
        # Incumbent low-pri job vs new high-pri job, capacity for one.
        # Full re-solve: high priority wins the node, incumbent is evicted.
        jobs = [
            JobRow(gpu=1, priority=0, current_node=0),
            JobRow(gpu=1, priority=100),
        ]
        nodes = [NodeRow(gpu_free=1, mem_free_gib=10)]
        p, _ = encode_problem(jobs, nodes)
        a = solve_greedy(p)
        assert int(a.node[1]) == 0
        assert int(a.node[0]) == -1


class TestGang:
    def test_incomplete_gang_unwound(self):
        # Gang of 3 x 2 chips but only 4 chips total -> nothing placed,
        # capacity fully returned.
        jobs = [JobRow(gpu=2, gang=7) for _ in range(3)]
        nodes = [NodeRow(gpu_free=2, mem_free_gib=10) for _ in range(2)]
        p, _ = encode_problem(jobs, nodes)
        a = solve_greedy(p)
        assert (np.asarray(a.node)[:3] == -1).all()
        np.testing.assert_allclose(np.asarray(a.gpu_free)[:2], [2, 2])

    def test_complete_gang_placed(self):
        jobs = [JobRow(gpu=2, gang=3) for _ in range(2)]
        nodes = [NodeRow(gpu_free=2, mem_free_gib=10) for _ in range(2)]
        p, _ = encode_problem(jobs, nodes)
        a = solve_greedy(p)
        assert (np.asarray(a.node)[:2] >= 0).all()

    def test_distinct_large_gang_ids_not_merged(self):
        # Gang ids >= J used to clip together in _gang_repair, merging
        # distinct gangs and unwinding feasible placements (review finding).
        jobs = [JobRow(gpu=1, gang=70), JobRow(gpu=1, gang=70), JobRow(gpu=16, gang=100)]
        nodes = [NodeRow(gpu_free=4, mem_free_gib=10)]
        p, _ = encode_problem(jobs, nodes)
        a = solve_greedy(p)
        assert (np.asarray(a.node)[:2] >= 0).all()
        assert int(a.node[2]) == -1

    def test_fill_pass_drains_contested_freed_node(self):
        # Worst case for the fill pass: a gang unwind frees ONE big node
        # while more small jobs contend for it than any fixed round cap —
        # the node is over-subscribed, so it accepts ~1 bidder per round
        # and settlement needs ~#jobs rounds. A fixed 16-round fill budget
        # silently re-stranded capacity here (r2 review finding); the
        # budget now scales with the fillable-job count.
        jobs = [JobRow(gpu=40, gang=5), JobRow(gpu=40, gang=5)] + [
            JobRow(gpu=1) for _ in range(50)
        ]
        nodes = [
            NodeRow(gpu_free=40, mem_free_gib=4096),
            NodeRow(gpu_free=0, mem_free_gib=4096),
        ]
        p, _ = encode_problem(jobs, nodes)
        a = solve_greedy(p)
        out = np.asarray(a.node)
        # gang can't fully place (only one 40-chip node) -> unwound
        assert (out[:2] == -1).all()
        # the freed 40 chips must be fully drained by the small jobs
        assert (out[2:52] >= 0).sum() == 40
        assert float(np.asarray(a.gpu_free)[0]) == 0.0

    def test_gang_capacity_freed_for_others(self):
        # Gang that can't fully place must not strand capacity needed by a
        # feasible singleton... (single solve: singleton placed, gang rows -1)
        jobs = [JobRow(gpu=2, gang=0), JobRow(gpu=2, gang=0), JobRow(gpu=2, gang=0)]
        nodes = [NodeRow(gpu_free=2, mem_free_gib=10), NodeRow(gpu_free=2, mem_free_gib=10)]
        p, _ = encode_problem(jobs, nodes)
        a = solve_greedy(p)
        assert float(np.asarray(a.gpu_free)[:2].sum()) == 4.0


class TestChurnStability:
    def test_resolve_under_churn_keeps_incumbents(self):
        """BASELINE config 4: a full re-solve with incumbents + 10% churn
        must move almost no surviving replica (hysteresis + home-bid
        protections; measured ~0.2% at 10k x 1k, bound at 2% here)."""
        from kubeinfer_tpu.solver.problem import encode_problem_arrays

        rng = np.random.default_rng(11)
        J, N = 600, 64
        kw = dict(
            job_gpu=rng.integers(1, 8, J).astype(np.float32),
            job_mem_gib=rng.integers(4, 64, J).astype(np.float32),
            job_priority=rng.integers(0, 8, J).astype(np.float32),
            node_gpu_free=np.full(N, 64.0, np.float32),
            node_mem_free_gib=np.full(N, 512.0, np.float32),
        )
        first = solve_greedy(encode_problem_arrays(**kw))
        current = np.asarray(first.node)[:J].copy()
        assert (current >= 0).all()

        departed = rng.random(J) < 0.1
        current[departed] = -1
        kw["job_gpu"][departed] = rng.integers(1, 8, departed.sum())
        kw["job_priority"][departed] = rng.integers(0, 8, departed.sum())
        second = solve_greedy(
            encode_problem_arrays(**kw, job_current_node=current)
        )
        new = np.asarray(second.node)[:J]
        survivors = ~departed
        moved = (new[survivors] != current[survivors]).mean()
        assert moved < 0.02, f"{moved:.1%} of surviving incumbents moved"
        assert (new >= 0).all()  # churn replacements also all place


class TestGreedyRandom:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    @pytest.mark.parametrize("jn", [(40, 10), (200, 30)])
    def test_invariants_random(self, seed, jn):
        J, N = jn
        rng = np.random.default_rng(seed)
        jobs = [
            JobRow(
                gpu=float(rng.choice([0.5, 1, 2, 4])),
                mem_gib=float(rng.uniform(1, 40)),
                priority=float(rng.integers(0, 5)),
                model=f"m{rng.integers(0, 8)}",
            )
            for _ in range(J)
        ]
        nodes = [
            NodeRow(
                gpu_free=float(rng.choice([4, 8, 16])),
                mem_free_gib=float(rng.uniform(50, 200)),
                topology=int(rng.integers(0, 4)),
                cached_models=[f"m{m}" for m in rng.choice(8, size=2, replace=False)],
            )
            for _ in range(N)
        ]
        p, _ = encode_problem(jobs, nodes)
        a = solve_greedy(p)
        assert_invariants(p, jobs, nodes, a)
        greedy_fixpoint_check(jobs, nodes, a)
        # sanity: a healthy fraction places
        assert int(a.placed) > 0


class TestAuction:
    def test_matches_hungarian_total_cost(self):
        # One-to-one instance: J jobs, N >= J whole-node requests. Auction
        # total cost must be within J*eps of the Hungarian optimum.
        linear_sum_assignment = pytest.importorskip(
            "scipy.optimize"
        ).linear_sum_assignment

        rng = np.random.default_rng(42)
        J, N = 12, 16
        jobs = [JobRow(gpu=1, mem_gib=1, model=f"m{i % 5}") for i in range(J)]
        nodes = [
            NodeRow(
                gpu_free=1,
                mem_free_gib=4,
                cached_models=[f"m{m}" for m in rng.choice(5, size=2, replace=False)],
                topology=int(rng.integers(0, 3)),
            )
            for _ in range(N)
        ]
        p, _ = encode_problem(jobs, nodes)
        w = ScoreWeights()
        eps = 0.001
        a = solve_auction(p, w, eps=eps, max_iters=4096)
        assigned = np.asarray(a.node)[:J]
        assert (assigned >= 0).all()
        assert len(set(assigned.tolist())) == J, "auction double-booked a node"

        # oracle cost matrix (mirror of core._static_cost + fit terms)
        cached = np.zeros((N, 6), bool)
        for i, n in enumerate(nodes):
            for m in n.cached_models:
                cached[i, int(m[1:]) + 1] = True
        cost = np.zeros((J, N), np.float64)
        for j, job in enumerate(jobs):
            for i, n in enumerate(nodes):
                hit = cached[i, (j % 5) + 1]
                cost[j, i] = (
                    w.cache * (1.0 - float(hit))
                    + w.fit_gpu * (n.gpu_free - job.gpu) / max(n.gpu_free, 1.0)
                    + w.fit_mem
                    * (n.mem_free_gib - job.mem_gib)
                    / max(n.mem_free_gib, 1.0)
                )
        rows, cols = linear_sum_assignment(cost)
        opt = cost[rows, cols].sum()
        got = cost[np.arange(J), assigned].sum()
        assert got <= opt + J * eps + 1e-3, f"auction {got} vs optimal {opt}"

    def test_perfect_matching_places_all(self):
        """Completeness property (r3 verdict item 4): on instances with a
        perfect matching, placed == J — regardless of tie degeneracy
        (identical fleets), model-pocket price wars, or the iteration
        budget (the completeness fill guarantees the stragglers)."""
        from kubeinfer_tpu.solver.problem import encode_problem_arrays

        for seed in range(6):
            rng = np.random.default_rng(seed)
            J = int(rng.integers(50, 300))
            N = J + int(rng.integers(0, 50))
            p = encode_problem_arrays(
                # whole-node demands -> any free node hosts any job
                job_gpu=np.full(J, 16.0, np.float32),
                job_mem_gib=rng.integers(16, 128, J).astype(np.float32),
                job_model=rng.integers(0, 32, J).astype(np.int32),
                node_gpu_free=np.full(N, 16.0, np.float32),
                node_mem_free_gib=np.full(N, 128.0, np.float32),
                node_cached=(rng.random((N, 32)) < 0.05),
            )
            a = solve_auction(p, max_iters=256)
            assert int(a.placed) == J, (seed, int(a.placed), J)
            assigned = np.asarray(a.node)[:J]
            assert len(set(assigned.tolist())) == J  # one job per node

    def test_identical_fleet_converges_fast(self):
        """Tie-degenerate regression (r3: 995/1000 at the iteration cap):
        hash tie-breaking must spread bids so a fully identical fleet
        converges in a handful of iterations, not one-per-job."""
        from kubeinfer_tpu.solver.problem import encode_problem_arrays

        p = encode_problem_arrays(
            job_gpu=np.full(256, 8.0, np.float32),
            job_mem_gib=np.full(256, 8.0, np.float32),
            node_gpu_free=np.full(256, 8.0, np.float32),
            node_mem_free_gib=np.full(256, 64.0, np.float32),
        )
        a = solve_auction(p)
        assert int(a.placed) == 256
        assert int(a.rounds) < 20, int(a.rounds)

    def test_auction_respects_capacity_one(self):
        jobs = [JobRow(gpu=1, mem_gib=1) for _ in range(5)]
        nodes = [NodeRow(gpu_free=1, mem_free_gib=2) for _ in range(3)]
        p, _ = encode_problem(jobs, nodes)
        a = solve_auction(p)
        assigned = np.asarray(a.node)[:5]
        placed = assigned[assigned >= 0]
        assert len(set(placed.tolist())) == len(placed)
        assert len(placed) == 3


class TestZeroNoiseSpreading:
    def test_identical_jobs_spread_without_noise(self):
        """Regression: with noise=0, perfectly tied jobs must still spread
        bids across nodes instead of filling one node per round and hitting
        the round budget with feasible jobs unplaced."""
        import numpy as np
        from kubeinfer_tpu.solver.problem import encode_problem_arrays

        p = encode_problem_arrays(
            job_gpu=np.ones(200, np.float32),
            job_mem_gib=np.zeros(200, np.float32),
            node_gpu_free=np.full(40, 4.0, np.float32),
            node_mem_free_gib=np.full(40, 100.0, np.float32),
        )
        out = solve_greedy(p, ScoreWeights(noise=0.0))
        assert int(out.placed) == 160  # all capacity used (40 nodes x 4)


class TestPriorityGating:
    def test_high_priority_wins_node_discovered_late(self):
        """Regression: without priority-gated rounds, low-priority jobs
        commit capacity in round 1 on the one node a high-priority job only
        reaches in round 2 (after losing its first-choice conflict)."""
        import numpy as np
        from kubeinfer_tpu.solver.problem import encode_problem_arrays

        # 4 nodes of 8 chips. 4 high-prio jobs of 6 chips (must take one
        # node each) + 3 low-prio jobs of 4 chips (fit only if they get a
        # whole node, which they must NOT).
        p = encode_problem_arrays(
            job_gpu=np.array([6, 6, 6, 6, 4, 4, 4], np.float32),
            job_mem_gib=np.zeros(7, np.float32),
            job_priority=np.array([100, 100, 100, 100, 0, 0, 0], np.float32),
            node_gpu_free=np.full(4, 8.0, np.float32),
            node_mem_free_gib=np.full(4, 64.0, np.float32),
        )
        a = solve_greedy(p)
        nodes = np.asarray(a.node)
        assert (nodes[:4] >= 0).all(), nodes
        assert (nodes[4:] == -1).all(), nodes

    def test_padded_jobs_do_not_inflate_priority_classes(self):
        """Regression (advisor r1): padded rows sort last with +inf key and
        used to form a phantom priority class. With exactly fence_classes
        (4, see solve_greedy's class compression) distinct real priorities
        the scaled ranks then became {0,0,1,2}, merging the top two classes
        — the lower of which could steal capacity a top-class loser only
        discovers a round later."""
        import numpy as np
        from kubeinfer_tpu.solver.problem import encode_problem_arrays

        # 2 nodes x 8 chips. A,B (prio 300, 6 chips) both prefer node 0
        # (cache hit for model 1); the conflict loser discovers node 1 only
        # in the next round. C (prio 200, 4 chips) prefers node 1 (cache hit
        # for model 2): if classes 300/200 merge, C takes node 1 in round 1
        # and the loser of A/B can never place. D (100) and E (0) complete
        # the 4 distinct priority levels and fit the leftovers.
        node_cached = np.zeros((2, 4), bool)
        node_cached[0, 1] = True
        node_cached[1, 2] = True
        p = encode_problem_arrays(
            job_gpu=np.array([6, 6, 4, 1, 1], np.float32),
            job_mem_gib=np.zeros(5, np.float32),
            job_priority=np.array([300, 300, 200, 100, 0], np.float32),
            job_model=np.array([1, 1, 2, 3, 3], np.int32),
            node_gpu_free=np.full(2, 8.0, np.float32),
            node_mem_free_gib=np.full(2, 64.0, np.float32),
            node_cached=node_cached,
        )
        a = solve_greedy(p)
        nodes = np.asarray(a.node)
        assert (nodes[:2] >= 0).all(), nodes  # both top-class jobs placed
        assert nodes[2] == -1, nodes  # class-200 job must not fit
        assert (nodes[3:5] >= 0).all(), nodes  # 1-chip jobs fill leftovers


class TestPallasParity:
    def test_interpret_matches_jnp(self):
        """The Pallas round kernels (interpret mode on CPU) must place the
        same assignment as the jnp reference ops — they implement identical
        math, tile-by-tile."""
        import numpy as np
        from kubeinfer_tpu.solver.core import solve_greedy
        from kubeinfer_tpu.solver.problem import encode_problem_arrays

        rng = np.random.default_rng(3)
        J, N = 128, 128  # minimal 128-aligned shapes for the tiled kernels
        p = encode_problem_arrays(
            job_gpu=rng.integers(1, 8, J).astype(np.float32),
            job_mem_gib=rng.integers(4, 64, J).astype(np.float32),
            job_priority=rng.integers(0, 4, J).astype(np.float32),
            job_model=rng.integers(0, 16, J).astype(np.int32),
            # incumbents exercise the kernels' home-bid fence exemption
            job_current_node=np.where(
                rng.random(J) < 0.5, rng.integers(0, N, J), -1
            ).astype(np.int32),
            node_gpu_free=np.full(N, 16.0, np.float32),
            node_mem_free_gib=np.full(N, 128.0, np.float32),
            node_cached=(rng.random((N, 16)) < 0.1),
        )
        ref = solve_greedy(p, accel="jnp")
        pal = solve_greedy(p, accel="interpret")
        assert np.array_equal(np.asarray(ref.node), np.asarray(pal.node))
        assert int(ref.placed) == int(pal.placed)

    def test_interpret_matches_jnp_j_tiled(self, monkeypatch):
        """J-axis tiling (tiles_j > 1): the bid kernel's 2-D grid and the
        accept kernel's init-at-tj0/accumulate-across-tj logic must be
        bit-identical to the untiled jnp reference. MAX_TILE_J is patched
        small so the multi-tile path runs at test-sized shapes (in
        production it engages at any bucket over 1024 jobs — the common
        case)."""
        import numpy as np
        from kubeinfer_tpu.solver import pallas_kernels as pk
        from kubeinfer_tpu.solver.core import solve_greedy
        from kubeinfer_tpu.solver.problem import encode_problem_arrays

        monkeypatch.setattr(pk, "MAX_TILE_J", 128)
        rng = np.random.default_rng(9)
        J, N = 384, 128  # 3 J tiles of 128
        p = encode_problem_arrays(
            job_gpu=rng.integers(1, 8, J).astype(np.float32),
            job_mem_gib=rng.integers(4, 64, J).astype(np.float32),
            job_priority=rng.integers(0, 4, J).astype(np.float32),
            job_model=rng.integers(0, 16, J).astype(np.int32),
            job_current_node=np.where(
                rng.random(J) < 0.5, rng.integers(0, N, J), -1
            ).astype(np.int32),
            node_gpu_free=np.full(N, 16.0, np.float32),
            node_mem_free_gib=np.full(N, 128.0, np.float32),
            node_cached=(rng.random((N, 16)) < 0.1),
        )
        assert pk._tile_j(J) == 128  # multi-tile path engaged
        ref = solve_greedy(p, accel="jnp")
        pal = solve_greedy(p, accel="interpret")
        assert np.array_equal(np.asarray(ref.node), np.asarray(pal.node))
        assert int(ref.placed) == int(pal.placed)


class TestMegaSerializedGreedy:
    """The round-fusion mega path (class-serialized greedy): kernel/twin
    parity, hard invariants, priority semantics, churn stability. The
    mega algorithm is NOT bit-identical to the pipelined-fence loop (see
    pallas_kernels mega section); its contract is the same hard
    guarantees plus strict class-serialized priority order."""

    @staticmethod
    def _sorted_instance(seed, J=384, N=128, tight=False, gang_frac=0.2):
        from kubeinfer_tpu.solver.problem import encode_problem_arrays

        rng = np.random.default_rng(seed)
        order = np.argsort(-rng.integers(0, 8, J).astype(np.float32),
                           kind="stable")
        pr = rng.integers(0, 8, J).astype(np.float32)[order]
        return encode_problem_arrays(
            job_gpu=rng.integers(1, 8, J).astype(np.float32),
            job_mem_gib=rng.integers(4, 64, J).astype(np.float32),
            job_priority=pr,
            job_gang=np.where(
                rng.random(J) < gang_frac, rng.integers(0, 40, J), -1
            ).astype(np.int32),
            job_model=rng.integers(0, 16, J).astype(np.int32),
            job_current_node=np.where(
                rng.random(J) < 0.3, rng.integers(0, N, J), -1
            ).astype(np.int32),
            node_gpu_free=(
                rng.integers(4, 17, N) if tight else np.full(N, 16)
            ).astype(np.float32),
            node_mem_free_gib=np.full(N, 128.0, np.float32),
            node_cached=(rng.random((N, 16)) < 0.1),
        )

    @pytest.mark.parametrize("seed", [0, 1, 5])
    def test_interpret_matches_jnp_twin(self, seed):
        """Mosaic kernel (interpret mode) and the pure-jnp twin share
        _mega_round_math — outputs must be bit-identical."""
        p = self._sorted_instance(seed, tight=seed % 2 == 1)
        ref = solve_greedy(p, accel="mega-jnp")
        pal = solve_greedy(p, accel="mega-interpret")
        assert np.array_equal(np.asarray(ref.node), np.asarray(pal.node))
        assert int(ref.placed) == int(pal.placed)
        assert int(ref.rounds) == int(pal.rounds)

    def test_multi_class_windows(self, monkeypatch):
        """Force W < J so the class-window grid (and the capacity
        residency across grid steps) actually runs at test shapes."""
        from kubeinfer_tpu.solver import pallas_kernels as pk

        monkeypatch.setattr(pk, "_MEGA_S_BYTES", 128 * 128 * 4)
        assert pk.mega_window(128, 384) == 128  # 3 classes
        for seed in range(4):
            p = self._sorted_instance(seed, tight=True)
            ref = solve_greedy(p, accel="mega-jnp")
            pal = solve_greedy(p, accel="mega-interpret")
            assert np.array_equal(
                np.asarray(ref.node), np.asarray(pal.node)
            )

    @pytest.mark.parametrize("seed", range(8))
    def test_invariants_and_fixpoint(self, seed):
        from kubeinfer_tpu.solver.problem import encode_problem_arrays

        rng = np.random.default_rng(100 + seed)
        J = int(rng.integers(10, 200))
        N = int(rng.integers(2, 24))
        cap = float(rng.integers(4, 32))
        pr = -np.sort(-rng.integers(0, 6, J).astype(np.float32))
        kw = dict(
            job_gpu=rng.integers(1, max(2, int(cap)), J).astype(np.float32),
            job_mem_gib=rng.integers(1, 32, J).astype(np.float32),
            job_priority=pr,
            job_gang=np.where(
                rng.random(J) < 0.3, rng.integers(0, max(J // 4, 1), J), -1
            ).astype(np.int32),
            job_current_node=np.where(
                rng.random(J) < 0.4, rng.integers(0, N, J), -1
            ).astype(np.int32),
            node_gpu_free=np.full(N, cap, np.float32),
            node_mem_free_gib=np.full(N, 256.0, np.float32),
        )
        p = encode_problem_arrays(**kw)
        a = solve_greedy(p, accel="mega-jnp")
        assigned = np.asarray(a.node)[:J]
        for n in range(N):
            assert kw["job_gpu"][assigned == n].sum() <= cap + 1e-3
            assert kw["job_mem_gib"][assigned == n].sum() <= 256.0 + 1e-3
        gang = kw["job_gang"]
        for g in np.unique(gang[gang >= 0]):
            members = assigned[gang == g]
            assert (members >= 0).all() or (members < 0).all()
        gpu_left = np.asarray(a.gpu_free)[:N]
        mem_left = np.asarray(a.mem_free)[:N]
        for j in np.nonzero(assigned < 0)[0]:
            if gang[j] >= 0:
                continue
            fits = (kw["job_gpu"][j] <= gpu_left + 1e-3) & (
                kw["job_mem_gib"][j] <= mem_left + 1e-3
            )
            assert not fits.any(), (seed, int(j))

    def test_no_inversion_within_window(self):
        """Windows are VMEM-sized, not priority-aligned, so different
        priority levels share one window — the in-window fence must stop
        a low-priority job from committing capacity a high-priority job
        needs a round later (code-review r4 repro: without the fence,
        mega placed the priority-0 job and stranded a priority-10 one)."""
        from kubeinfer_tpu.solver.problem import encode_problem_arrays

        # Two 8-gpu nodes. H1, H2 (priority 10, 8 gpu, model cached on
        # node 0) and L (priority 0, 4 gpu, model cached on node 1) all
        # fit initially; if L grabs node 1 in round 1, H2 is stranded.
        cached = np.zeros((2, 4), bool)
        cached[0, 1] = True  # model 0 -> slot 1
        cached[1, 2] = True  # model 1 -> slot 2
        p = encode_problem_arrays(
            job_gpu=np.array([8.0, 8.0, 4.0], np.float32),
            job_mem_gib=np.array([8.0, 8.0, 4.0], np.float32),
            job_priority=np.array([10.0, 10.0, 0.0], np.float32),
            job_model=np.array([0, 0, 1], np.int32),
            node_gpu_free=np.array([8.0, 8.0], np.float32),
            node_mem_free_gib=np.array([64.0, 64.0], np.float32),
            node_cached=cached,
        )
        for accel in ("mega-jnp", "mega-interpret"):
            a = solve_greedy(p, accel=accel)
            nodes_out = np.asarray(a.node)[:3]
            assert (nodes_out[:2] >= 0).all(), (accel, nodes_out)
            assert nodes_out[2] == -1, (accel, nodes_out)

    def test_strict_class_priority_order(self):
        """Cross-window serialization gives the top class first pick of
        a contested node. The home-bid exemption does not invert THIS
        case: when the top-priority job bids the incumbent's node in the
        same round, rank-ordered acceptance still hands it the node."""
        from kubeinfer_tpu.solver.problem import encode_problem_arrays

        # One node with 8 chips. Top-priority newcomer needs all 8; a
        # low-priority incumbent lives there wanting 4. Sorted order puts
        # the newcomer first; serialized classes give it the node.
        p = encode_problem_arrays(
            job_gpu=np.array([8.0, 4.0], np.float32),
            job_mem_gib=np.array([8.0, 4.0], np.float32),
            job_priority=np.array([10.0, 0.0], np.float32),
            job_current_node=np.array([-1, 0], np.int32),
            node_gpu_free=np.array([8.0], np.float32),
            node_mem_free_gib=np.array([64.0], np.float32),
        )
        a = solve_greedy(p, accel="mega-jnp")
        assert int(a.node[0]) == 0, "top-priority job must win the node"
        assert int(a.node[1]) == -1

    def test_shrunk_node_releases_its_incumbents(self):
        """Seeding validates joint fit per node: a node whose free
        capacity no longer covers its incumbents releases ALL of them to
        re-bid (they relocate under the move penalty, not silently
        overcommit)."""
        from kubeinfer_tpu.solver.problem import encode_problem_arrays

        p = encode_problem_arrays(
            job_gpu=np.array([4.0, 4.0], np.float32),
            job_mem_gib=np.array([4.0, 4.0], np.float32),
            job_current_node=np.array([0, 0], np.int32),
            # node 0 shrank below its incumbents' joint demand
            node_gpu_free=np.array([6.0, 8.0], np.float32),
            node_mem_free_gib=np.array([64.0, 64.0], np.float32),
        )
        for accel in ("mega-jnp", "mega-interpret"):
            a = solve_greedy(p, accel=accel)
            nodes_out = np.asarray(a.node)[:2]
            assert (nodes_out >= 0).all(), (accel, nodes_out)
            # no overcommit: they cannot both sit on node 0
            assert sorted(nodes_out.tolist()) == [0, 1], (accel, nodes_out)

    @pytest.mark.parametrize("seed", range(5))
    def test_preemption_repair_fuzz(self, seed):
        """Property of the seeded solve + one-shot preemption repair: at
        exit, the HIGHEST-priority unplaced job (the repair's target
        selection) cannot be made to fit by unseating the strictly-
        lower-rank incumbents of any single node. Random tight instances
        with incumbents + arrivals; also re-checks overcommit."""
        from kubeinfer_tpu.solver.problem import encode_problem_arrays
        from kubeinfer_tpu.solver.core import _EPS

        rng = np.random.default_rng(200 + seed)
        J = int(rng.integers(40, 160))
        N = int(rng.integers(3, 12))
        cap = float(rng.integers(8, 24))
        pr = -np.sort(-rng.integers(0, 6, J).astype(np.float32))
        cur = np.where(
            rng.random(J) < 0.5, rng.integers(0, N, J), -1
        ).astype(np.int32)
        kw = dict(
            job_gpu=rng.integers(1, max(2, int(cap // 2)), J).astype(
                np.float32
            ),
            job_mem_gib=rng.integers(1, 16, J).astype(np.float32),
            job_priority=pr,
            job_current_node=cur,
            node_gpu_free=np.full(N, cap, np.float32),
            node_mem_free_gib=np.full(N, 128.0, np.float32),
        )
        p = encode_problem_arrays(**kw)
        a = solve_greedy(p, accel="mega-jnp")
        assigned = np.asarray(a.node)[:J]
        gf = np.asarray(a.gpu_free)[:N]
        mf = np.asarray(a.mem_free)[:N]
        for n in range(N):
            assert kw["job_gpu"][assigned == n].sum() <= cap + 1e-3

        # crank mirror of the solver's 4-class compression
        n_classes = len(np.unique(pr))
        dense = np.unique(-pr, return_inverse=True)[1]
        crank = np.minimum(dense * 4 // max(n_classes, 1), 3)
        unpl = np.nonzero(assigned < 0)[0]
        if unpl.size == 0:
            return
        # The repair targets the minimum ACCEPT KEY (full priority rank,
        # then demand DESCENDING, then index) — its exit property holds
        # for that job, so the mirror must select identically.
        dmax = max(kw["job_gpu"].max(), 1.0)
        demand_q = np.clip(
            kw["job_gpu"] * (15.0 / dmax), 0, 15
        ).astype(np.int64)
        jkey = (dense.astype(np.int64) << 40) | (
            (15 - demand_q) << 20
        ) | np.arange(J, dtype=np.int64)
        j_star = unpl[np.argmin(jkey[unpl])]
        # mirror of the solver's seating rule: only jobs seeded by the
        # per-node JOINT-fit check are unseatable victims (a job that
        # re-bid its old home through the rounds is not seated)
        at_home = cur >= 0
        ok_node = np.array([
            kw["job_gpu"][at_home & (cur == n)].sum() <= cap + 1e-4
            and kw["job_mem_gib"][at_home & (cur == n)].sum()
            <= 128.0 + 1e-4
            for n in range(N)
        ])
        seated_mask = (
            at_home
            & ok_node[np.clip(cur, 0, N - 1)]
            & (assigned == cur)
        )
        for n in range(N):
            victims = (
                seated_mask
                & (cur == n)
                & (crank > crank[j_star])
            )
            freed_g = kw["job_gpu"][victims].sum()
            freed_m = kw["job_mem_gib"][victims].sum()
            if freed_g + freed_m == 0:
                continue
            fits = (
                kw["job_gpu"][j_star] <= gf[n] + freed_g + _EPS
                and kw["job_mem_gib"][j_star] <= mf[n] + freed_m + _EPS
            )
            assert not fits, (
                seed, int(j_star), n, "repair left a reclaimable node"
            )

    def test_churn_stability(self):
        """Surviving incumbents stay put under 10% churn. Mega carries
        the same home-bid fence exemption as the pipelined path —
        without it, incumbents whose node interests a higher class get
        fenced off their own home every round and survivor moves
        measured 6.1% at the 10k bench shape (BENCH r4 pre-fix) against
        the ~0.2% stability contract (BASELINE config 4)."""
        from kubeinfer_tpu.solver.problem import encode_problem_arrays

        rng = np.random.default_rng(11)
        J, N = 600, 64
        pr = -np.sort(-rng.integers(0, 8, J).astype(np.float32))
        kw = dict(
            job_gpu=rng.integers(1, 8, J).astype(np.float32),
            job_mem_gib=rng.integers(4, 64, J).astype(np.float32),
            job_priority=pr,
            node_gpu_free=np.full(N, 64.0, np.float32),
            node_mem_free_gib=np.full(N, 512.0, np.float32),
        )
        first = solve_greedy(encode_problem_arrays(**kw), accel="mega-jnp")
        current = np.asarray(first.node)[:J].copy()
        assert (current >= 0).all()
        departed = rng.random(J) < 0.1
        current[departed] = -1
        kw["job_gpu"][departed] = rng.integers(1, 8, departed.sum())
        second = solve_greedy(
            encode_problem_arrays(**kw, job_current_node=current),
            accel="mega-jnp",
        )
        new = np.asarray(second.node)[:J]
        survivors = ~departed
        moved = (new[survivors] != current[survivors]).mean()
        assert moved < 0.02, f"{moved:.1%} of surviving incumbents moved"
        assert (new >= 0).all()


class TestPropertyFuzz:
    """Randomized invariant fuzz: gang + priority + incumbents + tight
    capacity, many seeds. Complements the targeted tests by walking the
    interaction space; seeds are fixed so failures replay."""

    def test_invariants_hold_across_random_instances(self):
        from kubeinfer_tpu.solver.problem import encode_problem_arrays

        for seed in range(12):
            rng = np.random.default_rng(seed)
            J = int(rng.integers(10, 200))
            N = int(rng.integers(2, 24))
            cap = float(rng.integers(4, 32))
            kw = dict(
                job_gpu=rng.integers(1, max(2, int(cap)), J).astype(np.float32),
                job_mem_gib=rng.integers(1, 32, J).astype(np.float32),
                job_priority=rng.integers(0, 6, J).astype(np.float32),
                job_gang=np.where(
                    rng.random(J) < 0.3, rng.integers(0, max(J // 4, 1), J), -1
                ).astype(np.int32),
                job_current_node=np.where(
                    rng.random(J) < 0.4, rng.integers(0, N, J), -1
                ).astype(np.int32),
                node_gpu_free=np.full(N, cap, np.float32),
                node_mem_free_gib=np.full(N, 256.0, np.float32),
            )
            p = encode_problem_arrays(**kw)
            a = solve_greedy(p)
            assigned = np.asarray(a.node)[:J]

            # capacity: both resources (memory binds on some seeds too)
            for n in range(N):
                used = kw["job_gpu"][assigned == n].sum()
                assert used <= cap + 1e-3, (seed, n, used)
                mem_used = kw["job_mem_gib"][assigned == n].sum()
                assert mem_used <= 256.0 + 1e-3, (seed, n, mem_used)
            # gang atomicity: every gang fully placed or fully unplaced
            gang = kw["job_gang"]
            for g in np.unique(gang[gang >= 0]):
                members = assigned[gang == g]
                assert (members >= 0).all() or (members < 0).all(), (
                    seed, int(g), members,
                )
            # Fixpoint completeness: an unplaced non-gang job must be
            # infeasible against the FINAL remaining capacity (the fill
            # pass guarantees this even after gang repair frees nodes).
            # Gang members are exempt: repair may unwind individually
            # feasible jobs, and the fill pass fences them by design.
            # (A strict priority non-inversion check — "no unplaced job
            # out-ranks a placed one whose node could host it" — is
            # deliberately NOT asserted: the fence prevents it per round,
            # but cross-round capacity commitment makes it heuristic.)
            gpu_left = np.asarray(a.gpu_free)[:N]
            mem_left = np.asarray(a.mem_free)[:N]
            for j in np.nonzero(assigned < 0)[0]:
                if gang[j] >= 0:
                    continue
                fits = (kw["job_gpu"][j] <= gpu_left + 1e-3) & (
                    kw["job_mem_gib"][j] <= mem_left + 1e-3
                )
                assert not fits.any(), (seed, int(j))


class TestPrankParity:
    """The sorted fast path and dense fallback of the priority rank must
    agree on every sorted input — the backend priority-sorts before
    packing, so production solves take the sorted path exclusively while
    most unit tests exercise the dense one; this is the bridge."""

    def test_sorted_matches_dense_on_sorted_inputs(self):
        import numpy as np
        import jax.numpy as jnp
        from kubeinfer_tpu.solver.core import _prank_dense, _prank_sorted

        rng = np.random.default_rng(3)
        cases = [
            np.sort(rng.integers(0, 8, 200).astype(np.float32)),
            np.sort(rng.normal(size=173).astype(np.float32)),
            np.zeros(64, np.float32),  # single class
            np.arange(50, dtype=np.float32),  # all distinct
            np.array([1.0], np.float32),  # J=1
        ]
        for neg_p in cases:
            # padded rows (inf) always sort last, as solve_greedy builds
            # them
            padded = np.concatenate([neg_p, [np.inf, np.inf]])
            got = np.asarray(_prank_sorted(jnp.asarray(padded)))
            want = np.asarray(_prank_dense(jnp.asarray(padded)))
            np.testing.assert_array_equal(got, want)

    def test_solve_sorted_path_equals_dense_path(self):
        """Same logical problem, sorted job order: a solve whose prank
        comes from the sorted path must equal one where the dense path is
        forced (by patching the sortedness predicate's branch)."""
        import numpy as np
        from kubeinfer_tpu.solver import core
        from kubeinfer_tpu.solver.core import solve_greedy
        from kubeinfer_tpu.solver.problem import encode_problem_arrays

        rng = np.random.default_rng(5)
        J, N = 200, 32
        pr = np.sort(rng.integers(0, 6, J).astype(np.float32))[::-1].copy()
        kw = dict(
            job_gpu=rng.integers(1, 8, J).astype(np.float32),
            job_mem_gib=rng.integers(4, 64, J).astype(np.float32),
            job_priority=pr,
            node_gpu_free=np.full(N, 32.0, np.float32),
            node_mem_free_gib=np.full(N, 256.0, np.float32),
        )
        p = encode_problem_arrays(**kw)
        a = solve_greedy(p, accel="jnp")
        orig = core._prank_sorted
        core._prank_sorted = core._prank_dense  # force dense either way
        try:
            b = solve_greedy(p, accel="jnp")
        finally:
            core._prank_sorted = orig
        np.testing.assert_array_equal(np.asarray(a.node), np.asarray(b.node))


class TestAuctionGangFill:
    """Auction's gang repair must re-offer freed capacity in the same
    solve (r2 verdict item 7): no feasible non-gang job left unplaced
    while gang-unwind capacity sits idle."""

    def test_freed_capacity_refilled_same_solve(self):
        # 2 whole-node gang jobs that can't BOTH place (one node busy
        # with a higher-benefit job? simpler: gang of 3, only 2 nodes
        # free for it) -> unwind frees nodes; a non-gang job must then
        # take one.
        jobs = [
            JobRow(gpu=8, mem_gib=32, gang=1),
            JobRow(gpu=8, mem_gib=32, gang=1),
            JobRow(gpu=8, mem_gib=32, gang=1),
            JobRow(gpu=8, mem_gib=32),  # non-gang filler
        ]
        nodes = [NodeRow(gpu_free=8, mem_free_gib=64) for _ in range(2)]
        p, _ = encode_problem(jobs, nodes)
        a = solve_auction(p)
        assigned = np.asarray(a.node)[:4]
        # the gang (needs 3 nodes, only 2 exist) fully unwinds
        assert (assigned[:3] == -1).all()
        # the filler must NOT be stranded next to two idle nodes
        assert assigned[3] >= 0
        assert_invariants(p, jobs, nodes, a)

    def test_fill_property_fuzz(self):
        rng = np.random.default_rng(17)
        for seed in range(6):
            r = np.random.default_rng(seed)
            J, N = 24, 16
            gang = np.where(
                r.random(J) < 0.5, r.integers(0, 4, J), -1
            ).astype(np.int32)
            jobs = [
                JobRow(
                    gpu=8, mem_gib=float(r.integers(8, 33)),
                    gang=int(gang[j]),
                )
                for j in range(J)
            ]
            nodes = [
                NodeRow(gpu_free=8, mem_free_gib=64) for _ in range(N)
            ]
            p, _ = encode_problem(jobs, nodes)
            a = solve_auction(p)
            assigned = np.asarray(a.node)[:J]
            gpu_left = np.asarray(a.gpu_free)[:N]
            mem_left = np.asarray(a.mem_free)[:N]
            # gang atomicity
            for g in set(gang[gang >= 0].tolist()):
                members = np.nonzero(gang == g)[0]
                placed = assigned[members] >= 0
                assert placed.all() or (~placed).all(), (seed, g)
            # the fill property: no unplaced feasible NON-gang job while
            # freed capacity could host it
            for j in np.nonzero(assigned < 0)[0]:
                if gang[j] >= 0:
                    continue
                fits = (jobs[j].gpu <= gpu_left + EPS) & (
                    jobs[j].mem_gib <= mem_left + EPS
                )
                assert not fits.any(), (seed, int(j))


class TestAuctionFusedParity:
    """The one-launch auction kernel (pk.auction_solve, interpret mode)
    must be BIT-identical to its jnp twin (core._auction_loop_jnp) —
    the CLAUDE.md kernel/twin invariant. Every arithmetic float in the
    kernel is either a selection of a twin-computed value or the same
    expression in the same order, so exact equality is the contract,
    not a tolerance."""

    def _rand_instance(self, seed, J, N):
        from kubeinfer_tpu.solver.core import INFEASIBLE, _auction_tiebreak

        rng = np.random.default_rng(seed)
        benefit = rng.normal(0.0, 3.0, (J, N)).astype(np.float32)
        infeas = rng.random((J, N)) < 0.25
        benefit = jnp.asarray(
            np.where(infeas, -float(INFEASIBLE), benefit), jnp.float32
        )
        valid = jnp.asarray(rng.random(J) < 0.9)
        return benefit, _auction_tiebreak(J, N), valid

    @pytest.mark.parametrize(
        "seed,J,N", [(0, 96, 128), (1, 128, 128), (2, 256, 384), (3, 8, 128)]
    )
    def test_kernel_matches_twin_bitwise(self, seed, J, N):
        from kubeinfer_tpu.solver import pallas_kernels as pk
        from kubeinfer_tpu.solver.core import (
            _STALE_ITERS,
            _TIE_TOL,
            INFEASIBLE,
            _auction_loop_jnp,
        )

        benefit, tiebreak, valid = self._rand_instance(seed, J, N)
        eps = jnp.float32(0.01)
        want_asg, want_it = _auction_loop_jnp(
            benefit, tiebreak, valid, eps, 512
        )
        got_asg, got_it = pk.auction_solve(
            benefit, tiebreak, valid, eps,
            max_iters=512, stale_iters=_STALE_ITERS, tie_tol=_TIE_TOL,
            neg=-float(INFEASIBLE), interpret=True,
        )
        np.testing.assert_array_equal(
            np.asarray(got_asg), np.asarray(want_asg)
        )
        assert int(got_it) == int(want_it)

    def test_solve_auction_accel_interpret_matches_jnp(self):
        """End-to-end: solve_auction under accel='interpret' (fused loop
        via the interpreter + pallas fill kernels) places the same jobs
        as accel='jnp' on an aligned instance."""
        from kubeinfer_tpu.solver.problem import encode_problem_arrays

        rng = np.random.default_rng(5)
        J, N = 128, 128
        p = encode_problem_arrays(
            job_gpu=rng.integers(1, 8, J).astype(np.float32),
            job_mem_gib=rng.integers(1, 32, J).astype(np.float32),
            job_model=rng.integers(0, 16, J).astype(np.int32),
            node_gpu_free=np.full(N, 16.0, np.float32),
            node_mem_free_gib=np.full(N, 64.0, np.float32),
            node_cached=(rng.random((N, 16)) < 0.2),
        )
        a_jnp = solve_auction(p, accel="jnp")
        a_int = solve_auction(p, accel="interpret")
        np.testing.assert_array_equal(
            np.asarray(a_int.node), np.asarray(a_jnp.node)
        )
        assert int(a_int.placed) == int(a_jnp.placed)

    def test_explicit_pallas_on_ineligible_shape_fails_loudly(self):
        """An explicit Pallas-flavored accel must not silently fall back
        to the twin (that would make kernel parity tests vacuous)."""
        from kubeinfer_tpu.solver.core import _auction_accel

        with pytest.raises(ValueError, match="auction kernel needs"):
            _auction_accel("interpret", 100, 64)  # J%8 ok? 100%8=4 -> no
        assert _auction_accel("jnp", 100, 64) == ""
        assert _auction_accel("auto", 100, 64) == ""
